"""Server endpoint layer (server/ rebuilt): every channel endpoint the
framework answers.

Endpoint table mirrors the reference exactly (server/index.js:28-37,
server/protocol/index.js:22-35, server/admin/index.js:24-68):
``/protocol/join|ping|ping-req``, ``/proxy/req``, ``/health``, 13 admin
endpoints, and ``/trace/add|remove``.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

from ringpop_tpu.gossip.ping_sender import send_ping
from ringpop_tpu.net.channel import RemoteError
from ringpop_tpu.utils import errors
from ringpop_tpu.utils.trace import TraceError, Tracer


def _err(e: errors.RingpopError) -> RemoteError:
    return RemoteError(e.to_dict())


class RingpopServer:
    def __init__(self, ringpop: Any, channel):
        self.ringpop = ringpop
        self.channel = channel
        r = channel.register
        # protocol (server/protocol/index.js:22-35)
        r("/protocol/join", self.protocol_join)
        r("/protocol/ping", self.protocol_ping)
        r("/protocol/ping-req", self.protocol_ping_req)
        # forwarding + health (server/index.js:34-37)
        r("/proxy/req", self.proxy_req)
        r("/health", self.health)
        # admin (server/admin/index.js:24-68; /admin/metrics is this
        # port's addition — Prometheus text next to the JSON stats)
        r("/admin/stats", self.admin_stats)
        r("/admin/metrics", self.admin_metrics)
        r("/admin/lookup", self.admin_lookup)
        r("/admin/reload", self.admin_reload)
        r("/admin/debugSet", self.admin_debug_set)
        r("/admin/debugClear", self.admin_debug_clear)
        r("/admin/gossip", self.admin_gossip_start)  # legacy alias
        r("/admin/gossip/start", self.admin_gossip_start)
        r("/admin/gossip/stop", self.admin_gossip_stop)
        r("/admin/gossip/tick", self.admin_gossip_tick)
        r("/admin/gossip/status", self.admin_gossip_status)
        r("/admin/tick", self.admin_gossip_tick)  # legacy alias
        r("/admin/join", self.admin_member_join)
        r("/admin/leave", self.admin_member_leave)
        r("/admin/member/join", self.admin_member_join)
        r("/admin/member/leave", self.admin_member_leave)
        r("/admin/config/get", self.admin_config_get)
        r("/admin/config/set", self.admin_config_set)
        # trace (server/trace.js)
        r("/trace/add", self.trace_add)
        r("/trace/remove", self.trace_remove)

    # -- protocol ---------------------------------------------------------

    def protocol_join(self, head, body) -> Tuple[Any, Any]:
        """Join validation + full-membership reply
        (server/protocol/join.js:53-135)."""
        ringpop = self.ringpop
        body = body or {}
        app, source = body.get("app"), body.get("source")
        incarnation = body.get("incarnationNumber")
        if app is None or source is None or incarnation is None:
            raise _err(errors.PropertyRequiredError(
                property="app/source/incarnationNumber"))
        if ringpop.joins_denied():
            raise _err(errors.DenyJoinError())
        if source == ringpop.whoami():
            raise _err(errors.InvalidJoinSourceError(actual=source))
        if app != ringpop.app:
            raise _err(errors.InvalidJoinAppError(
                expected=ringpop.app, actual=app))
        for pattern in ringpop.config.get("memberBlacklist") or []:
            if pattern.search(source):
                raise _err(errors.BlacklistedError(member=source))

        ringpop.server_rate.mark()
        ringpop.total_rate.mark()
        ringpop.stat("increment", "join.recv")
        ringpop.membership.make_alive(source, incarnation)
        return None, {
            "app": ringpop.app,
            "coordinator": ringpop.whoami(),
            "membership": ringpop.dissemination.full_sync(),
            "membershipChecksum": ringpop.membership.checksum,
        }

    def protocol_ping(self, head, body) -> Tuple[Any, Any]:
        """Apply piggybacked changes, respond with receiver changes
        (server/protocol/ping.js:24-51)."""
        ringpop = self.ringpop
        body = body or {}
        ringpop.server_rate.mark()
        ringpop.total_rate.mark()
        ringpop.stat("increment", "ping.recv")
        if not ringpop.is_ready:
            raise _err(errors.InvalidLocalMemberError())
        changes = body.get("changes") or []
        if changes:
            ringpop.membership.update(changes)
        res_changes, _ = ringpop.dissemination.issue_as_receiver(
            body.get("source"),
            body.get("sourceIncarnationNumber"),
            body.get("checksum"),
        )
        return None, {"changes": res_changes}

    def protocol_ping_req(self, head, body) -> Tuple[Any, Any]:
        """Ping the target on the requester's behalf
        (server/protocol/ping-req.js:25-69)."""
        ringpop = self.ringpop
        body = body or {}
        ringpop.server_rate.mark()
        ringpop.total_rate.mark()
        ringpop.stat("increment", "ping-req.recv")
        if not ringpop.is_ready:
            raise _err(errors.InvalidLocalMemberError())
        changes = body.get("changes") or []
        if changes:
            ringpop.membership.update(changes)
        target = body.get("target")
        if target is None:
            raise _err(errors.PropertyRequiredError(property="target"))
        ringpop.stat("increment", "ping-req.other-members")
        ok, _ = send_ping(ringpop, {"address": target})
        res_changes, _ = ringpop.dissemination.issue_as_receiver(
            body.get("source"),
            body.get("sourceIncarnationNumber"),
            body.get("checksum"),
        )
        return None, {
            "changes": res_changes,
            "pingStatus": ok,
            "target": target,
        }

    # -- forwarding + health ---------------------------------------------

    def proxy_req(self, head, body) -> Tuple[Any, Any]:
        try:
            res = self.ringpop.request_proxy.handle_request(head or {}, body)
        except errors.RingpopError as e:
            raise _err(e)
        return None, res

    def health(self, head, body) -> Tuple[Any, Any]:
        return None, "ok"

    # -- admin ------------------------------------------------------------

    def admin_stats(self, head, body) -> Tuple[Any, Any]:
        return None, self.ringpop.get_stats()

    def admin_metrics(self, head, body) -> Tuple[Any, Any]:
        """Prometheus text exposition of this node's state (the modern
        collector-facing twin of /admin/stats).  The body is the plain
        exposition string; content-type negotiation is the HTTP
        gateway's concern, not the channel's."""
        from ringpop_tpu.obs.prometheus import render_ringpop_metrics

        return {"contentType": "text/plain; version=0.0.4"}, (
            render_ringpop_metrics(self.ringpop)
        )

    def admin_lookup(self, head, body) -> Tuple[Any, Any]:
        key = (body or {}).get("key")
        if key is None:
            raise _err(errors.LookupKeyRequiredError())
        return None, {"dest": self.ringpop.lookup(key)}

    def admin_reload(self, head, body) -> Tuple[Any, Any]:
        fname = (body or {}).get("file")
        if fname:
            self.ringpop._seed_bootstrap_hosts(fname)
        return None, {"status": "ok"}

    def admin_debug_set(self, head, body) -> Tuple[Any, Any]:
        flag = (body or {}).get("debugFlag")
        if flag:
            self.ringpop.set_debug_flag(flag)
        return None, {"status": "ok"}

    def admin_debug_clear(self, head, body) -> Tuple[Any, Any]:
        self.ringpop.clear_debug_flags()
        return None, {"status": "ok"}

    def admin_gossip_start(self, head, body) -> Tuple[Any, Any]:
        self.ringpop.gossip.start()
        return None, {"status": "ok"}

    def admin_gossip_stop(self, head, body) -> Tuple[Any, Any]:
        self.ringpop.gossip.stop()
        return None, {"status": "ok"}

    def admin_gossip_tick(self, head, body) -> Tuple[Any, Any]:
        self.ringpop.gossip.tick()
        return None, {"checksum": self.ringpop.membership.checksum}

    def admin_gossip_status(self, head, body) -> Tuple[Any, Any]:
        return None, {"status": "stopped" if self.ringpop.gossip.is_stopped else "running"}

    def admin_member_join(self, head, body) -> Tuple[Any, Any]:
        """Rejoin a left node (server/admin/member.js:44-51)."""
        ringpop = self.ringpop
        local = ringpop.membership.local_member
        if local is None:
            raise _err(errors.InvalidLocalMemberError())
        ringpop.membership.make_alive(local.address, ringpop.timers.now_ms())
        ringpop.gossip.start()
        ringpop.suspicion.reenable()
        return None, {"status": "rejoined"}

    def admin_member_leave(self, head, body) -> Tuple[Any, Any]:
        """Graceful leave (server/admin/member.js, §3.5)."""
        ringpop = self.ringpop
        local = ringpop.membership.local_member
        if local is None:
            raise _err(errors.InvalidLocalMemberError())
        if local.status == "leave":
            raise _err(errors.RedundantLeaveError())
        ringpop.membership.make_leave(
            local.address, local.incarnation_number
        )
        return None, {"status": "ok"}

    def admin_config_get(self, head, body) -> Tuple[Any, Any]:
        return None, self.ringpop.config.get_all()

    def admin_config_set(self, head, body) -> Tuple[Any, Any]:
        for key, value in (body or {}).items():
            self.ringpop.config.set(key, value)
        return None, {"status": "ok"}

    # -- trace ------------------------------------------------------------

    def trace_add(self, head, body) -> Tuple[Any, Any]:
        body = body or {}
        try:
            tracer = Tracer(
                self.ringpop,
                body.get("event"),
                body.get("sink") or {},
                body.get("expiresIn"),
            )
        except TraceError as e:
            raise RemoteError({"type": "ringpop.trace.invalid", "message": str(e)})
        self.ringpop.tracers.add(tracer)
        return None, {"status": "ok"}

    def trace_remove(self, head, body) -> Tuple[Any, Any]:
        body = body or {}
        removed = self.ringpop.tracers.remove(
            body.get("event"), body.get("sink") or {}
        )
        return None, {"status": "ok" if removed else "not-found"}
