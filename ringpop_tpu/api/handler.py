"""Sharded-channel handler (ringpop-handler.js rebuilt).

Wraps an application endpoint handler so requests carrying a shard key in
the ``sk`` head field route through the ring: local keys are handled
in-process, remote keys relay to their owner over the same endpoint
(ringpop-handler.js:73-104).  Endpoints on the blacklist pass straight
through (ringpop-handler.js:52-68).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence, Tuple

from ringpop_tpu.net.channel import RemoteError


class RingpopHandler:
    def __init__(
        self,
        ringpop: Any,
        handler: Callable[[Any, Any], Tuple[Any, Any]],
        endpoint: str,
        blacklist: Optional[Sequence[str]] = None,
        timeout_s: float = 30.0,
    ):
        self.ringpop = ringpop
        self.handler = handler
        self.endpoint = endpoint
        self.blacklist = set(blacklist or [])
        self.timeout_s = timeout_s

    def register(self, channel=None) -> None:
        (channel or self.ringpop.channel).register(self.endpoint, self)

    def __call__(self, head: Any, body: Any) -> Tuple[Any, Any]:
        if self.endpoint in self.blacklist:
            return self.handler(head, body)
        sk = (head or {}).get("sk") if isinstance(head, dict) else None
        if sk is None:
            self.ringpop.logger.warning(
                "ringpop handler got request without a shard key",
                extra={"endpoint": self.endpoint},
            )
            return self.handler(head, body)
        dest = self.ringpop.lookup(sk)
        if dest == self.ringpop.whoami():
            return self.handler(head, body)
        # relay to the owner (ringpop-handler.js:101-103)
        self.ringpop.stat("increment", "handler.relay")
        return self.ringpop.channel.request(
            dest, self.endpoint, head=head, body=body, timeout_s=self.timeout_s
        )
