"""Sharding the SWIM simulator over a ``jax.sharding.Mesh``.

The reference scales by spawning one OS process per node and wiring them with
TChannel RPC (scripts/tick-cluster.js:472-479 spawns N processes;
docs/architecture_design.md's deployment model is one ringpop per service
instance).  The TPU-native analog: the N-node axis of the batched simulator
is **sharded over the device mesh**, and the gossip exchange — gathers along
the target axis, segment-reductions onto receivers — lowers to XLA
collectives (all-gather / reduce-scatter / all-to-all) that ride ICI between
chips of a slice and DCN between hosts.

Design:

- One logical mesh axis, ``"nodes"``, shards the *observer* dimension: every
  ``[N]`` array is ``P("nodes")`` and every ``[N, N]`` view/change table is
  ``P("nodes", None)`` — node i's whole view lives on one chip, so the SWIM
  update rule (a per-(observer, subject) elementwise gate) is entirely local.
  Cross-chip traffic is exactly the protocol's message plane: delivering
  piggybacked changes to ping targets (a segment-reduce over the target
  index) and reading target/ping-req peer liveness (gathers along the
  observer axis).  That is the same locality structure the reference has —
  per-node state local, pings on the wire — mapped onto the mesh.
- The mesh can be any shape; multi-host meshes (ICI within a slice, DCN
  across slices) work unchanged because GSPMD partitions the same program.
  Per the scaling-book recipe: pick the mesh, annotate shardings on inputs
  and outputs, let XLA insert the collectives.
- ``jax.jit`` with explicit in/out shardings compiles ONE SPMD program; no
  per-node Python, no host round-trips inside a protocol period.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.recovery import CheckpointableMixin, CheckpointSpec
from ringpop_tpu.ops import checksum_encode as ce

AXIS = "nodes"


def make_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence] = None,
    axis: str = AXIS,
) -> Mesh:
    """A 1-D mesh over ``n_devices`` (default: all available devices)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def make_mesh_2d(
    n_hosts: int,
    chips_per_host: int,
    devices: Optional[Sequence] = None,
    axes: Sequence[str] = ("dcn", "ici"),
) -> Mesh:
    """A 2-D (hosts x chips) mesh — the multi-host topology: the outer axis
    crosses DCN between hosts, the inner axis rides ICI within a slice.
    The node dimension shards over BOTH (a tuple PartitionSpec axis), so
    the same SPMD program spans slices the way the reference's TChannel
    cluster spans machines."""
    need = n_hosts * chips_per_host
    if devices is None:
        devices = jax.devices()[:need]  # default pool: take what we need
    if len(devices) != need:
        raise ValueError(
            "need exactly %d devices for a %dx%d mesh, have %d"
            % (need, n_hosts, chips_per_host, len(devices))
        )
    grid = np.asarray(devices).reshape(n_hosts, chips_per_host)
    return Mesh(grid, tuple(axes))


def _node_axis(mesh: Mesh):
    """The PartitionSpec axis entry sharding the node dimension: the mesh's
    single axis name, or the tuple of all axes for multi-D meshes."""
    names = mesh.axis_names
    return names[0] if len(names) == 1 else tuple(names)


def _spec_for(x, axis) -> P:
    """Shard the leading (observer/node) axis; replicate scalars."""
    if getattr(x, "ndim", 0) == 0:
        return P()
    return P(axis, *([None] * (x.ndim - 1)))


def state_shardings(mesh: Mesh, state: engine.SimState):
    """NamedSharding pytree for a SimState: node axis sharded, rest local."""
    axis = _node_axis(mesh)
    return jax.tree.map(
        lambda x: NamedSharding(mesh, _spec_for(x, axis)), state
    )


def inputs_shardings(mesh: Mesh, inputs: engine.TickInputs):
    axis = _node_axis(mesh)
    return jax.tree.map(
        lambda x: NamedSharding(mesh, _spec_for(x, axis)), inputs
    )


def shard_state(state: engine.SimState, mesh: Mesh) -> engine.SimState:
    """Place a SimState onto the mesh with the node axis distributed."""
    return jax.device_put(state, state_shardings(mesh, state))


def _abstract_state(params: engine.SimParams, universe=None):
    """Shape-only SimState (no arrays built) for deriving shardings.
    Unfused checksum modes share one shape, so evaluate in fast mode —
    the farmhash mode requires a universe to seed the checksum cache,
    which a shape probe neither has nor needs.  Fused mode DOES change
    the state shape (the [N, N, R] record cache, R universe-dependent),
    so it traces the real init with the universe."""
    if params.fused_checksum == "on" and universe is not None:
        return jax.eval_shape(
            lambda: engine.init_state(params, universe=universe)
        )
    shape_params = params._replace(
        checksum_mode="fast", fused_checksum="off"
    )
    return jax.eval_shape(lambda: engine.init_state(shape_params))


def _replicated_metrics(mesh: Mesh):
    fields = len(engine.TickMetrics._fields)
    return jax.tree.map(
        lambda _: NamedSharding(mesh, P()), engine.TickMetrics(*[0] * fields)
    )


@functools.lru_cache(maxsize=None)
def make_sharded_tick(
    params: engine.SimParams, universe: ce.Universe, mesh: Mesh
):
    """Compile ``engine.tick`` as one SPMD program over the mesh.

    Returns ``f(state, inputs) -> (state, metrics)`` with state kept
    device-resident and node-sharded across ticks.  lru_cached on the
    (hashable) params/universe/mesh triple, like the single-device
    drivers: fresh ShardedSim instances with the same config reuse the
    compiled executable instead of re-tracing.
    """
    st_sh = state_shardings(mesh, _abstract_state(params, universe))
    in_sh = inputs_shardings(mesh, engine.TickInputs.quiet(params.n))
    metrics_sh = _replicated_metrics(mesh)
    fn = functools.partial(engine.tick, params=params, universe=universe)
    return jax.jit(
        fn, in_shardings=(st_sh, in_sh), out_shardings=(st_sh, metrics_sh)
    )


@functools.lru_cache(maxsize=None)
def make_sharded_scan(
    params: engine.SimParams, universe: ce.Universe, mesh: Mesh
):
    """Compile a ``lax.scan`` of the tick over a [T, N] event schedule.
    lru_cached like :func:`make_sharded_tick`."""
    st_sh = state_shardings(mesh, _abstract_state(params, universe))
    axis = _node_axis(mesh)
    sched_sh = jax.tree.map(
        lambda x: NamedSharding(mesh, P(None, axis)),
        engine.TickInputs.quiet(params.n),
    )
    metrics_sh = _replicated_metrics(mesh)

    def scanned(state, inputs):
        def body(st, inp):
            return engine.tick(st, inp, params, universe)

        return jax.lax.scan(body, state, inputs)

    return jax.jit(
        scanned,
        in_shardings=(st_sh, sched_sh),
        out_shardings=(st_sh, metrics_sh),
    )


def clear_executable_cache() -> None:
    """Drop the shared compiled SPMD executables (sweep hygiene, like the
    single-device drivers' clear hooks)."""
    make_sharded_tick.cache_clear()
    make_sharded_scan.cache_clear()
    _storm_tick_fn.cache_clear()
    _storm_scan_fn.cache_clear()
    make_exchange_plane.cache_clear()


class ShardedSim(CheckpointableMixin):
    """A SimCluster-shaped driver whose state lives sharded on the mesh.

    The multi-chip twin of :class:`ringpop_tpu.models.sim.cluster.SimCluster`:
    same bootstrap/step/run surface, but every array carries a NamedSharding
    and the compiled tick is one SPMD program across all devices.
    """

    def __init__(
        self,
        n: int,
        mesh: Optional[Mesh] = None,
        params: Optional[engine.SimParams] = None,
        addresses: Optional[Sequence[str]] = None,
        seed: int = 0,
    ):
        from ringpop_tpu.models.sim.cluster import default_addresses

        self.mesh = mesh if mesh is not None else make_mesh()
        if addresses is None:
            addresses = default_addresses(n)
        self.universe = ce.Universe.from_addresses(addresses)
        self.params = params or engine.SimParams(n=self.universe.n)
        # pin trace-env-dependent params (hash_impl="env",
        # parity_recompute="auto") to concrete values, exactly like
        # SimCluster: the shared executable caches below key on params,
        # and a trace-time env read would serve stale lowerings across
        # RINGPOP_TPU_PALLAS toggles
        from ringpop_tpu.models.sim.cluster import _resolve_hash_impl

        requested_fused_tick = self.params.fused_tick
        self.params = _resolve_hash_impl(self.params)
        # sharded-aware fused_tick pin (engine.resolve_sharded_fused_tick,
        # the resolve_sharded_exchange analog): a pallas_call does not
        # partition under GSPMD, so the sharded tick runs the
        # partitionable xla twin instead — observable, never silent
        import jax as _jax

        self.params = self.params._replace(
            fused_tick=engine.resolve_sharded_fused_tick(
                self.params._replace(fused_tick=requested_fused_tick),
                _jax.default_backend(),
            )
        )
        from ringpop_tpu.ops import toolkit as _toolkit

        self._fused_tick_note = _toolkit.resolution_note(
            "fused_tick",
            requested_fused_tick,
            self.params.fused_tick,
            _jax.default_backend(),
            single_device_resolution=engine.resolve_fused_tick(
                self.params._replace(fused_tick=requested_fused_tick),
                _jax.default_backend(),
            ),
            shards=int(self.mesh.devices.size),
        )
        if self.params.n % self.mesh.devices.size:
            raise ValueError(
                "n=%d not divisible by mesh size %d"
                % (self.params.n, self.mesh.devices.size)
            )
        self.state = shard_state(
            engine.init_state(self.params, seed=seed, universe=self.universe),
            self.mesh,
        )
        self._tick = make_sharded_tick(self.params, self.universe, self.mesh)

        self._scan = make_sharded_scan(self.params, self.universe, self.mesh)
        # count of bounded-parity overflow replays, like SimCluster's — a
        # window that replayed paid the exact-shape cost too
        self.parity_replays = 0

    def fused_tick_resolution(self) -> dict:
        """The sharded fused-tick resolution as a runlog-ready dict —
        ``differs_from_single_device`` flags the auto-on-TPU case where
        the mesh dropped the pallas kernels to the partitionable xla
        twin (observable, like the round-14 exchange note)."""
        return dict(self._fused_tick_note)

    def bootstrap(self):
        inputs = engine.TickInputs.quiet(self.params.n)._replace(
            join=jnp.ones(self.params.n, bool)
        )
        return self.step(inputs)

    def _exact_params(self) -> engine.SimParams:
        """Exact-recompute twin for bounded-parity overflow replays (same
        contract as SimCluster's — see engine.SimParams.parity_recompute;
        fused runs always replay under "full")."""
        return self.params._replace(
            parity_recompute=engine.resolve_exact_recompute(
                self.params, jax.default_backend()
            )
        )

    def _maybe_replay_exact(self, pre, metrics, make_fn, inputs):
        """Bounded-parity overflow fallback, shared by step/run: discard
        the overflowed result and replay from the pre-run state under the
        exact twin program (same contract as SimCluster's)."""
        bounded = (
            self.params.checksum_mode == "farmhash"
            and self.params.parity_recompute == "bounded"
        )
        if not bounded or not int(np.asarray(metrics.parity_overflow).sum()):
            return None
        self.parity_replays += 1
        return make_fn(self._exact_params(), self.universe, self.mesh)(
            pre, inputs
        )

    def step(self, inputs: Optional[engine.TickInputs] = None):
        if inputs is None:
            inputs = engine.TickInputs.quiet(self.params.n)
        pre = self.state
        self.state, metrics = self._tick(pre, inputs)
        replayed = self._maybe_replay_exact(
            pre, metrics, make_sharded_tick, inputs
        )
        if replayed is not None:
            self.state, metrics = replayed
        self._after_ticks(1)
        return jax.tree.map(np.asarray, metrics)

    def run(self, schedule) -> engine.TickMetrics:
        return self._run_chunked(schedule, self._run_window)

    def _run_window(self, schedule) -> engine.TickMetrics:
        inputs = schedule.as_inputs()
        pre = self.state
        self.state, metrics = self._scan(pre, inputs)
        replayed = self._maybe_replay_exact(
            pre, metrics, make_sharded_scan, inputs
        )
        if replayed is not None:
            self.state, metrics = replayed
        return jax.tree.map(np.asarray, metrics)

    def checksums(self) -> np.ndarray:
        return np.asarray(self.state.checksum)

    # -- checkpoint/resume (models/sim/recovery.py) -----------------------
    # Saves gather the node-sharded state to host and split it across
    # per-shard files (default: one per mesh device); loads reassemble
    # full arrays and re-place them under THIS mesh's shardings, so a
    # checkpoint restores onto any device count — including down to the
    # single-device SimCluster (tests/parallel/test_sharded_ckpt.py).

    def _default_ckpt_shards(self) -> int:
        return int(self.mesh.devices.size)

    def _ckpt_spec(self) -> CheckpointSpec:
        return CheckpointSpec(
            engine.SimState, self.params, self._ckpt_sharded_fields()
        )

    def _ckpt_states(self):
        # live (sharded) state: the manager/save layer makes the ONE
        # host copy (recovery.host_copy_states) — copying here too would
        # memcpy the full state twice per cadence save
        return self.state

    def _ckpt_sharded_fields(self) -> frozenset:
        # every non-scalar SimState field is node-leading (_spec_for)
        return frozenset(
            f
            for f in self.state._fields
            if getattr(getattr(self.state, f), "ndim", 0) >= 1
        )

    def _ckpt_install(self, state) -> None:
        from ringpop_tpu.models.sim.cluster import fixup_sim_state

        self.state = shard_state(
            fixup_sim_state(state, self.params, self.universe), self.mesh
        )

    def save(self, path: str, shards: Optional[int] = None) -> None:
        """Manifest-format checkpoint directory at ``path``."""
        from ringpop_tpu.models.sim import checkpoint as ckpt
        from ringpop_tpu.models.sim.recovery import host_copy_states

        ckpt.save_checkpoint(
            path,
            host_copy_states(self.state),
            self.params,
            shards=self._default_ckpt_shards() if shards is None else shards,
            sharded_fields=self._ckpt_sharded_fields(),
        )

    def load(self, path: str) -> None:
        """Resume from ``path`` — a legacy ``.npz`` file or a manifest
        checkpoint directory (any shard count) alike."""
        from ringpop_tpu.models.sim import checkpoint as ckpt

        self._ckpt_install(
            ckpt.load_any(path, engine.SimState, self.params)
        )


# ---------------------------------------------------------------------------
# Scalable (rumor-table) engine over the mesh — the 1M-on-v5e-8 path.
# Node-indexed arrays shard over the mesh; the bounded rumor table, rng,
# and base_sum are tiny and replicate.  Since round 14 the gossip
# exchange's partner-row delivery is an EXPLICIT shard_map'd collective
# program (make_exchange_plane below) instead of GSPMD-inferred gathers;
# the limb-matmul checksum shards by rows with the [U, 4] limb table
# replicated.
# ---------------------------------------------------------------------------


# the ONE cap definition lives in ops/exchange.py next to the traffic
# model that charges the capped buffers; re-exported here because the
# cap is an attribute of the plane this module builds
from ringpop_tpu.ops.exchange import exchange_cap  # noqa: E402


def _route_rows_stats(rows, dest_l, src_l, axis: str, cap: int):
    """:func:`_route_rows` plus the routing statistics the telemetry
    plane drains: returns ``(routed, counts, overflow)`` where
    ``counts`` is this shard's [S] destination-bucket occupancy (before
    capping — mask- and cap-independent) and ``overflow`` the pmax-
    agreed fallback verdict.  The stats are byproducts of the routing
    math itself, so the plain wrapper traces the identical program.

    Fast path: bucket local rows by destination shard, pad each bucket
    to the static ``cap``, one ``all_to_all`` for the row payloads plus
    one for the [S, cap] destination-position plane, then scatter the
    received rows into place (a permutation: no write conflicts, every
    local position filled exactly once).  Overflow — any bucket fuller
    than ``cap``, pmax'd so every shard agrees — falls back to the
    bit-identical all-gather route: gather the full array and read row
    ``src_l[i]`` (``src`` = the analytic inverse of ``dest``, evaluated
    by the caller from the PRP).  Both paths deliver exactly
    ``rows[src_l]``; bitwise equality is pinned with a forced cap=1 in
    tests/parallel/test_shard_exchange.py."""
    n_shards = jax.lax.psum(1, axis)
    local = rows.shape[0]
    dshard = dest_l // jnp.int32(local)
    dpos = dest_l - dshard * jnp.int32(local)
    onehot = (
        dshard[:, None] == jnp.arange(n_shards, dtype=jnp.int32)[None, :]
    ).astype(jnp.int32)
    counts = jnp.sum(onehot, axis=0)
    slot = jnp.cumsum(onehot, axis=0) - 1  # rank within my dest bucket
    myslot = jnp.take_along_axis(slot, dshard[:, None], axis=1)[:, 0]
    overflow = jax.lax.pmax(jnp.any(counts > jnp.int32(cap)), axis)

    def a2a(_):
        buf = jnp.zeros((n_shards, cap, rows.shape[1]), rows.dtype)
        pos = jnp.full((n_shards, cap), -1, jnp.int32)
        # mode="drop": a slot past the cap is only reachable when the
        # overflow cond picked the other branch — this branch's scatter
        # must still trace to a safe program
        in_cap = myslot < jnp.int32(cap)
        row_sh = jnp.where(in_cap, dshard, n_shards)
        buf = buf.at[row_sh, myslot].set(rows, mode="drop")
        pos = pos.at[row_sh, myslot].set(dpos, mode="drop")
        rbuf = jax.lax.all_to_all(buf, axis, 0, 0)
        rpos = jax.lax.all_to_all(pos, axis, 0, 0)
        flat = rpos.reshape(-1)
        out = jnp.zeros_like(rows)
        return out.at[jnp.where(flat >= 0, flat, local)].set(
            rbuf.reshape(-1, rows.shape[1]), mode="drop"
        )

    def gather_fallback(_):
        full = jax.lax.all_gather(rows, axis, axis=0, tiled=True)
        return full[src_l]

    routed = jax.lax.cond(overflow, gather_fallback, a2a, None)
    return routed, counts, overflow


def _route_rows(rows, dest_l, src_l, axis: str, cap: int):
    """Deliver row ``g`` of the sharded array to global row ``dest[g]``
    — the stats-free view of :func:`_route_rows_stats` (same traced
    program; the unused stats fall to dead-code elimination)."""
    return _route_rows_stats(rows, dest_l, src_l, axis, cap)[0]


@functools.lru_cache(maxsize=None)
def make_exchange_plane(
    mesh: Mesh,
    impl: str,
    cap: Optional[int] = None,
    n: Optional[int] = None,
    metrics: bool = False,
):
    """The shard_map'd direct-round exchange plane for the scalable
    engine (the round-14 tentpole), matching the engine seam
    ``plane(heard, r_delta, active_words, direct_ok, partner0,
    inv_base) -> (new_heard, d_direct)``.

    Inside the body each shard holds its local ``[N/S, U/32]`` heard
    tile plus the LOCAL slices of the analytic PRP permutation
    (``partner0``/``inv_base`` are elementwise Feistel evaluations, so
    GSPMD keeps them shard-local; shard_map's in_specs hand each shard
    its rows' global partner ids).  The plane then:

    1. routes the pull rows — row ``p`` to ``inv_base[p]`` — and the
       direct_ok-masked push rows — row ``j`` to ``partner0[j]`` — with
       one explicit :func:`_route_rows` each (all_to_all, statically
       capped, all-gather overflow fallback);
    2. applies the receiver-side direct_ok mask to the pulls and the
       active-rumor word mask to both planes (same semantics, same
       order of exact bitwise ops as the inline engine path);
    3. runs the fused megakernel on the purely shard-local tiles
       (:func:`ringpop_tpu.ops.exchange.exchange_local`, ``impl`` =
       "pallas" on TPU / the "xla" twin elsewhere) — one VMEM pass per
       shard, no GSPMD drop-to-XLA.

    ``cap=None`` sizes the all_to_all buckets with :func:`exchange_cap`
    (then ``n`` must be given); an explicit cap is the overflow-fallback
    test lever.  lru_cached on the (hashable) arguments so storm tick
    and scan programs share one plane per configuration."""
    if impl not in ("pallas", "xla"):
        raise ValueError("plane impl must be pallas|xla, got %r" % (impl,))
    axis = _node_axis(mesh)
    shards = int(mesh.devices.size)
    if cap is None:
        if n is None:
            raise ValueError("make_exchange_plane needs cap= or n=")
        if n % shards:
            raise ValueError(
                "n=%d not divisible by %d shards" % (n, shards)
            )
        cap = exchange_cap(n // shards, shards)
    # tuple axis names (2-D meshes) collapse to one logical axis for the
    # collectives: shard_map over all axes with the node dim split
    # across them in order, so a single flat axis list is equivalent
    axes = axis if isinstance(axis, tuple) else (axis,)

    from ringpop_tpu.ops import exchange as _exch

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(axis, None),  # heard
            P(),  # r_delta (replicated rumor table)
            P(),  # active_words
            P(axis),  # direct_ok
            P(axis),  # partner0
            P(axis),  # inv_base
        ),
        out_specs=(P(axis, None), P(axis)),
        check_rep=False,
    )
    def plane(h_l, r_delta, active_words, ok_l, fwd_l, inv_l):
        # pull: row p -> inv[p]; receiver gates on its own direct_ok
        pulled = _route_rows(h_l, inv_l, fwd_l, axes, cap)
        pulled = (
            jnp.where(ok_l[:, None], pulled, 0) & active_words[None, :]
        )
        # push: sender gates on its own direct_ok; row j -> partner0[j]
        pushed = _route_rows(
            jnp.where(ok_l[:, None], h_l, 0), fwd_l, inv_l, axes, cap
        )
        pushed = pushed & active_words[None, :]
        return _exch.exchange_local(
            h_l, pulled, pushed, r_delta, impl=impl
        )

    if not metrics:
        return plane

    from ringpop_tpu.ops import histogram as hg

    t_pull = _exch.EXCH_HIST_TRACKS.index("cap_util_pull")
    t_push = _exch.EXCH_HIST_TRACKS.index("cap_util_push")

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            P(axis, None),  # heard
            P(),  # r_delta (replicated rumor table)
            P(),  # active_words
            P(axis),  # direct_ok
            P(axis),  # partner0
            P(axis),  # inv_base
            P(axis, None),  # exch counters [S, len(EXCH_COUNTERS)]
            P(axis, None, None),  # exch_hist [S, tracks, NBUCKETS]
        ),
        out_specs=(
            P(axis, None),
            P(axis),
            P(axis, None),
            P(axis, None, None),
        ),
        check_rep=False,
    )
    def plane_metrics(
        h_l, r_delta, active_words, ok_l, fwd_l, inv_l, exch_l, eh_l
    ):
        # identical trajectory math to `plane` above — same routing
        # calls, same mask order — plus write-only counter/histogram
        # bumps from the routing stats that are byproducts anyway
        local = h_l.shape[0]
        pulled, cnt_pull, ovf_pull = _route_rows_stats(
            h_l, inv_l, fwd_l, axes, cap
        )
        pulled = (
            jnp.where(ok_l[:, None], pulled, 0) & active_words[None, :]
        )
        pushed, cnt_push, ovf_push = _route_rows_stats(
            jnp.where(ok_l[:, None], h_l, 0), fwd_l, inv_l, axes, cap
        )
        pushed = pushed & active_words[None, :]

        one = jnp.uint32(1)
        # every sum pins dtype=uint32: under x64 jnp.sum would widen
        # to uint64 and break the scan carry (exch is a uint32 plane)
        # pull rows materialised here = my own direct_ok count
        pull_rows = jnp.sum(ok_l.astype(jnp.uint32), dtype=jnp.uint32)
        # push rows RECEIVED here: psum each shard's ok-masked
        # per-destination send tally, then read my own slot (shard id
        # = axis_index folded over the mesh axes in P() split order)
        dst = fwd_l // jnp.int32(local)
        sent = jnp.sum(
            jnp.where(
                ok_l[:, None],
                (
                    dst[:, None]
                    == jnp.arange(shards, dtype=jnp.int32)[None, :]
                ).astype(jnp.uint32),
                jnp.uint32(0),
            ),
            axis=0,
            dtype=jnp.uint32,
        )
        recv = jax.lax.psum(sent, axes)
        idx = jnp.int32(0)
        for a in axes:
            idx = idx * jnp.int32(mesh.shape[a]) + jax.lax.axis_index(a)
        push_rows = recv[idx]
        # EXCH_COUNTERS order is the wire format — keep in lockstep
        bump = jnp.stack(
            [
                one,  # ticks
                one * (~ovf_pull).astype(jnp.uint32),  # a2a_pull
                one * (~ovf_push).astype(jnp.uint32),  # a2a_push
                one * ovf_pull.astype(jnp.uint32),  # fallback_pull
                one * ovf_push.astype(jnp.uint32),  # fallback_push
                pull_rows,
                push_rows,
                jnp.sum((cnt_pull > 0), dtype=jnp.uint32),
                jnp.sum((cnt_push > 0), dtype=jnp.uint32),
            ]
        )
        eh0 = hg.record(
            eh_l[0], t_pull, cnt_pull, jnp.ones_like(cnt_pull, bool)
        )
        eh0 = hg.record(
            eh0, t_push, cnt_push, jnp.ones_like(cnt_push, bool)
        )
        new_h, d_direct = _exch.exchange_local(
            h_l, pulled, pushed, r_delta, impl=impl
        )
        return new_h, d_direct, exch_l + bump[None, :], eh0[None]

    return plane_metrics


# node-indexed ScalableState fields (sharded); everything else — the
# bounded [U] rumor table, the scalar clock/base, the rng — replicates.
# Single source: engine_scalable.NODE_SHARDED_FIELDS (shared with the
# sharded checkpoint split, models/sim/recovery.py)
from ringpop_tpu.models.sim.engine_scalable import (  # noqa: E402
    NODE_SHARDED_FIELDS as _SCALABLE_NODE_FIELDS,
)


def scalable_state_shardings(mesh: Mesh, params):
    from ringpop_tpu.models.sim import engine_scalable as es

    axis = _node_axis(mesh)
    abstract = jax.eval_shape(lambda: es.init_state(params))

    def _spec(f):
        a = getattr(abstract, f)
        if f in _SCALABLE_NODE_FIELDS:
            return P(axis, *([None] * (a.ndim - 1)))
        # per-shard telemetry planes shard over the mesh axis only when
        # their leading dim IS the mesh size (exchange_metrics=shards,
        # the shard_map-plane mode); any other divisor replicates
        if (
            f in es.SHARD_SHARDED_FIELDS
            and a is not None
            and a.shape[0] == int(mesh.devices.size)
        ):
            return P(axis, *([None] * (a.ndim - 1)))
        return P()

    return type(abstract)(
        **{
            f: NamedSharding(mesh, _spec(f))
            for f in abstract._fields
        }
    )


def _storm_input_shardings(mesh, inputs, leading_time_axis: bool):
    axis = _node_axis(mesh)
    spec = P(None, axis) if leading_time_axis else P(axis)
    return jax.tree.map(lambda _: NamedSharding(mesh, spec), inputs)


def _storm_metrics_shardings(mesh):
    from ringpop_tpu.models.sim import engine_scalable as es

    m_fields = len(es.ScalableMetrics._fields)
    return jax.tree.map(
        lambda _: NamedSharding(mesh, P()),
        es.ScalableMetrics(*[0] * m_fields),
    )


def _storm_sample_inputs(n: int, structure_key):
    """A ChurnInputs pytree with the same STRUCTURE as the caller's (the
    optional partition/leave fields change the arg tree)."""
    import jax.numpy as _jnp

    from ringpop_tpu.models.sim import engine_scalable as es

    no_partition, no_leave = structure_key
    inputs = es.ChurnInputs.quiet(n)
    if not no_partition:
        inputs = inputs._replace(partition=_jnp.zeros(n, _jnp.int32))
    if not no_leave:
        inputs = inputs._replace(leave=_jnp.zeros(n, bool))
    return inputs


def _storm_plane(mesh: Mesh, params, plane_key):
    """Resolve a ShardedStorm plane_key — None (gspmd modes) or
    ``(kernel_impl, cap-or-None, metrics)`` — to the shared compiled
    plane."""
    if plane_key is None:
        return None
    impl, cap, metrics = plane_key
    return make_exchange_plane(
        mesh, impl, cap=cap, n=params.n, metrics=metrics
    )


@functools.lru_cache(maxsize=None)
def _storm_tick_fn(params, mesh: Mesh, structure_key, plane_key=None):
    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import donate_state_argnums

    st_sh = scalable_state_shardings(mesh, params)
    in_sh = _storm_input_shardings(
        mesh, _storm_sample_inputs(params.n, structure_key), False
    )
    return jax.jit(
        functools.partial(
            es.tick,
            params=params,
            exchange_plane=_storm_plane(mesh, params, plane_key),
        ),
        in_shardings=(st_sh, in_sh),
        out_shardings=(st_sh, _storm_metrics_shardings(mesh)),
        # the round-10 in-place heard-mask update, kept intact under the
        # collective plane (backend-gated: CPU stays copy-safe — see
        # storm.donate_state_argnums; alias surface pinned as the
        # donation prong's mesh-storm-tick entry, DONATION_BUDGET.json)
        donate_argnums=donate_state_argnums(),
    )


@functools.lru_cache(maxsize=None)
def _storm_scan_fn(params, mesh: Mesh, structure_key, plane_key=None):
    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim.storm import donate_state_argnums

    st_sh = scalable_state_shardings(mesh, params)
    in_sh = _storm_input_shardings(
        mesh, _storm_sample_inputs(params.n, structure_key), True
    )
    plane = _storm_plane(mesh, params, plane_key)

    def scanned(state, inp):
        def body(st, i):
            return es.tick(st, i, params, exchange_plane=plane)

        return jax.lax.scan(body, state, inp)

    return jax.jit(
        scanned,
        in_shardings=(st_sh, in_sh),
        out_shardings=(st_sh, _storm_metrics_shardings(mesh)),
        donate_argnums=donate_state_argnums(),
    )


class ShardedStorm(CheckpointableMixin):
    """ScalableCluster over a device mesh: one SPMD program per tick/scan.

    The driver behind the 1M churn-storm north-star's v5e-8 configuration:
    same step/run surface as
    :class:`ringpop_tpu.models.sim.storm.ScalableCluster`, with every
    node-indexed array ``P("nodes")``-sharded and the trajectory bitwise
    equal to the single-device engine (tests/parallel/test_mesh.py)."""

    def __init__(
        self,
        n,
        mesh=None,
        params=None,
        seed: int = 0,
        exchange_cap_override: Optional[int] = None,
    ):
        from ringpop_tpu.models.sim import engine_scalable as es

        self.mesh = mesh if mesh is not None else make_mesh()
        self.params = params or es.ScalableParams(n=n)
        if self.params.n != n:
            self.params = self.params._replace(n=n)
        backend = jax.default_backend()
        shards = int(self.mesh.devices.size)
        # pin trace-time "auto" knobs exactly like ScalableCluster: the
        # module-level executable caches key on params, and the SPMD
        # trajectory must stay bitwise equal to the single-device engine
        # regardless of which backend resolved first.  The exchange is
        # MESH-AWARE since round 14 (es.resolve_sharded_exchange, full
        # table pinned in tests/parallel/test_shard_exchange.py):
        # "auto"/"pallas" resolve to the shard_map'd collective plane —
        # explicit all_to_all partner-row delivery + the fused
        # megakernel on shard-local tiles — instead of the PR-5 silent
        # drop to the XLA twin; "xla" keeps the partitionable GSPMD twin
        # as the fallback gate, "off" the classic inline phases.
        requested = self.params.fused_exchange
        self._single_device_resolution = es.resolve_fused_exchange(
            self.params, backend
        )
        mode, impl = es.resolve_sharded_exchange(
            self.params, backend, shards
        )
        self.exchange_mode = mode  # "shard_map" | "gspmd"
        self.exchange_impl = impl  # kernel impl (plane) / engine value
        self.exchange_cap = (
            (
                exchange_cap(n // shards, shards)
                if exchange_cap_override is None
                else exchange_cap_override
            )
            if mode == "shard_map"
            else None
        )
        # the metrics flag rides the plane key: the telemetry-carrying
        # plane is a DIFFERENT shard_map program (8-in/4-out), cached
        # separately in make_exchange_plane's lru table
        self._plane_key = (
            (
                impl,
                exchange_cap_override,
                bool(self.params.exchange_metrics),
            )
            if mode == "shard_map"
            else None
        )
        # the params the ENGINE traces with: under the plane the seam
        # bypasses fused_exchange, but pin it to the per-shard kernel so
        # artifacts/checkpoints record what actually ran (the field is
        # trajectory-neutral — checkpoint._TRAJECTORY_NEUTRAL_PARAMS)
        self.params = es.resolve_scalable_params(self.params, backend)
        if mode == "shard_map":
            self.params = self.params._replace(fused_exchange=impl)
        # the satellite-1 observability note: what "auto" would have
        # done on a single device vs what the mesh resolution picked —
        # surfaced through attach_recorder instead of the old silent
        # drop.  ``differs_from_single_device`` compares the KERNEL
        # (impl vs the single-device pick), not the routing mode: the
        # PR-5 problem was the computation silently changing lowering,
        # and the plane itself is not a divergence — on TPU auto runs
        # the same pallas megakernel under the plane, flag 0; on CPU
        # auto swaps the inline phases for the xla twin, flag 1.  The
        # routing mode rides the note separately as ``mode``.
        self._resolution_note = {
            "requested": requested,
            "mode": mode,
            "impl": impl,
            "shards": shards,
            "cap": self.exchange_cap,
            "single_device_resolution": self._single_device_resolution,
            "differs_from_single_device": (
                requested == "auto"
                and impl != self._single_device_resolution
            ),
        }
        if n % shards:
            raise ValueError(
                "n=%d not divisible by mesh size %d" % (n, shards)
            )
        if mode == "shard_map" and self.params.exchange_metrics not in (
            0,
            shards,
        ):
            # the plane accumulates one counter row per MESH shard; a
            # foreign bucket count would silently mislabel the wire
            raise ValueError(
                "exchange_metrics=%d must equal the mesh size (%d) under "
                "the shard_map plane (or 0 to disable)"
                % (self.params.exchange_metrics, shards)
            )
        self._st_sh = scalable_state_shardings(self.mesh, self.params)
        self.state = jax.device_put(
            es.init_state(self.params, seed=seed), self._st_sh
        )
        # optional telemetry sink (obs.RunRecorder via attach_recorder)
        self.recorder = None
        # jitted fns are resolved per input-pytree structure (ChurnInputs'
        # optional partition/leave change the arg tree) from MODULE-LEVEL
        # caches shared across instances, like the single-device drivers

    def exchange_resolution(self) -> dict:
        """The mesh-aware fused-exchange resolution, as a runlog-ready
        dict (mode/impl/cap/shards + the single-device comparison)."""
        return dict(self._resolution_note)

    def attach_recorder(self, recorder) -> None:
        """Attach an obs.RunRecorder: step()/run() metrics fold into it,
        and the mesh exchange resolution lands as a
        ``mesh_exchange_resolution`` event row immediately — the
        observable replacement for the PR-5 silent drop-to-XLA."""
        from ringpop_tpu.ops import toolkit

        recorder.describe(
            "sim.engine_scalable[mesh]", self.params.n, self.params
        )
        toolkit.emit_resolution(
            self._resolution_note,
            recorder=recorder,
            event="mesh_exchange_resolution",
        )
        self.recorder = recorder

    def emit_resolution_stat(self, bridge) -> None:
        """Publish the resolution to a statsd bridge (gauges under
        ``sharded.exchange.*``): 1/0 flags a mesh-vs-single-device
        divergence of the "auto" pick, plus the static all_to_all cap.
        The gauge shape is the toolkit's shared emitter — every fused-op
        resolver in the repo publishes the same way (ops.toolkit)."""
        from ringpop_tpu.ops import toolkit

        toolkit.emit_resolution(
            self._resolution_note,
            statsd=bridge,
            gauge_prefix="sharded.exchange",
        )

    def _structure_key(self, inputs):
        return (inputs.partition is None, inputs.leave is None)

    def step(self, inputs=None):
        from ringpop_tpu.models.sim import engine_scalable as es

        if inputs is None:
            inputs = es.ChurnInputs.quiet(self.params.n)
        tick = _storm_tick_fn(
            self.params,
            self.mesh,
            self._structure_key(inputs),
            self._plane_key,
        )
        self.state, m = tick(self.state, inputs)
        m = jax.tree.map(np.asarray, m)
        if self.recorder is not None:
            self.recorder.record_ticks(m)
        self._after_ticks(1)
        return m

    def run(self, schedule):
        return self._run_chunked(schedule, self._run_window)

    def _run_window(self, schedule):
        inputs = schedule.as_inputs()
        scan = _storm_scan_fn(
            self.params,
            self.mesh,
            self._structure_key(inputs),
            self._plane_key,
        )
        self.state, ms = scan(self.state, inputs)
        ms = jax.tree.map(np.asarray, ms)
        if self.recorder is not None:
            self.recorder.record_ticks(ms)
        return ms

    def checksums(self) -> np.ndarray:
        from ringpop_tpu.models.sim import engine_scalable as es

        if not bool(self.params.checksum_in_tick):
            return np.asarray(es.compute_checksums(self.state, self.params))
        return np.asarray(self.state.checksum)

    # -- exchange telemetry (ScalableParams.exchange_metrics) -------------

    def drain_exchange_metrics(self, reset: bool = True, statsd=None):
        """Drain the per-shard exchange telemetry plane (counters +
        cap-utilization histograms) through the shared host half
        (obs.exchange_stats.drain): per-shard ``mesh.exchange.drain``
        runlog rows on the attached recorder, ``sharded.exchange.*``
        statsd keys, wire-byte totals for the traffic-model gate.
        ``reset`` zeroes the device counters AFTER the sinks ran."""
        if self.state.exch is None:
            raise ValueError(
                "exchange telemetry is off — construct with "
                "ScalableParams(exchange_metrics=<mesh size>)"
            )
        from ringpop_tpu.obs import exchange_stats as oxs
        from ringpop_tpu.ops import exchange as _exch

        counters = np.asarray(self.state.exch)
        hist = np.asarray(self.state.exch_hist)
        s = int(counters.shape[0])
        summary = oxs.drain(
            counters,
            hist,
            w=int(self.state.heard.shape[1]),
            cap=self.exchange_cap,
            local_rows=self.params.n // s,
            source="sim.engine_scalable[mesh]",
            recorder=self.recorder,
            statsd=statsd,
        )
        if reset:
            self.state = self.state._replace(
                exch=jax.device_put(
                    _exch.init_exchange_counters(s), self._st_sh.exch
                ),
                exch_hist=jax.device_put(
                    _exch.init_exchange_hist(s), self._st_sh.exch_hist
                ),
            )
        return summary

    # -- checkpoint/resume (models/sim/recovery.py) -----------------------
    # Node-sharded fields (engine_scalable.NODE_SHARDED_FIELDS) split
    # across per-shard files — one per mesh device by default; the rumor
    # table/rng/base replicate into the common file.  Restores reassemble
    # and re-place under THIS mesh's shardings, so a 8-shard save resumes
    # on any device count (bitwise vs the single-file path — the gate in
    # tests/parallel/test_sharded_ckpt.py).

    def _default_ckpt_shards(self) -> int:
        return int(self.mesh.devices.size)

    def _ckpt_spec(self) -> CheckpointSpec:
        from ringpop_tpu.models.sim import engine_scalable as es

        return CheckpointSpec(
            es.ScalableState, self.params, es.NODE_SHARDED_FIELDS
        )

    def _ckpt_states(self):
        # live state; the save layer makes the one host copy
        return self.state

    def _ckpt_install(self, state) -> None:
        from ringpop_tpu.models.sim.storm import fixup_scalable_state

        self.state = jax.device_put(
            fixup_scalable_state(state, self.params), self._st_sh
        )

    def save(self, path: str, shards: Optional[int] = None) -> None:
        """Manifest-format checkpoint directory at ``path``."""
        from ringpop_tpu.models.sim import checkpoint as ckpt
        from ringpop_tpu.models.sim import engine_scalable as es
        from ringpop_tpu.models.sim.recovery import host_copy_states

        ckpt.save_checkpoint(
            path,
            host_copy_states(self.state),
            self.params,
            shards=self._default_ckpt_shards() if shards is None else shards,
            sharded_fields=es.NODE_SHARDED_FIELDS,
        )

    def load(self, path: str) -> None:
        """Resume from ``path`` — a legacy ``.npz`` file or a manifest
        checkpoint directory (any shard count) alike."""
        from ringpop_tpu.models.sim import checkpoint as ckpt
        from ringpop_tpu.models.sim import engine_scalable as es

        self._ckpt_install(
            ckpt.load_any(path, es.ScalableState, self.params)
        )
