"""Sharding the SWIM simulator over a ``jax.sharding.Mesh``.

The reference scales by spawning one OS process per node and wiring them with
TChannel RPC (scripts/tick-cluster.js:472-479 spawns N processes;
docs/architecture_design.md's deployment model is one ringpop per service
instance).  The TPU-native analog: the N-node axis of the batched simulator
is **sharded over the device mesh**, and the gossip exchange — gathers along
the target axis, segment-reductions onto receivers — lowers to XLA
collectives (all-gather / reduce-scatter / all-to-all) that ride ICI between
chips of a slice and DCN between hosts.

Design:

- One logical mesh axis, ``"nodes"``, shards the *observer* dimension: every
  ``[N]`` array is ``P("nodes")`` and every ``[N, N]`` view/change table is
  ``P("nodes", None)`` — node i's whole view lives on one chip, so the SWIM
  update rule (a per-(observer, subject) elementwise gate) is entirely local.
  Cross-chip traffic is exactly the protocol's message plane: delivering
  piggybacked changes to ping targets (a segment-reduce over the target
  index) and reading target/ping-req peer liveness (gathers along the
  observer axis).  That is the same locality structure the reference has —
  per-node state local, pings on the wire — mapped onto the mesh.
- The mesh can be any shape; multi-host meshes (ICI within a slice, DCN
  across slices) work unchanged because GSPMD partitions the same program.
  Per the scaling-book recipe: pick the mesh, annotate shardings on inputs
  and outputs, let XLA insert the collectives.
- ``jax.jit`` with explicit in/out shardings compiles ONE SPMD program; no
  per-node Python, no host round-trips inside a protocol period.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ringpop_tpu.models.sim import engine
from ringpop_tpu.models.sim.recovery import CheckpointableMixin, CheckpointSpec
from ringpop_tpu.ops import checksum_encode as ce

AXIS = "nodes"


def make_mesh(
    n_devices: Optional[int] = None,
    devices: Optional[Sequence] = None,
    axis: str = AXIS,
) -> Mesh:
    """A 1-D mesh over ``n_devices`` (default: all available devices)."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), (axis,))


def make_mesh_2d(
    n_hosts: int,
    chips_per_host: int,
    devices: Optional[Sequence] = None,
    axes: Sequence[str] = ("dcn", "ici"),
) -> Mesh:
    """A 2-D (hosts x chips) mesh — the multi-host topology: the outer axis
    crosses DCN between hosts, the inner axis rides ICI within a slice.
    The node dimension shards over BOTH (a tuple PartitionSpec axis), so
    the same SPMD program spans slices the way the reference's TChannel
    cluster spans machines."""
    need = n_hosts * chips_per_host
    if devices is None:
        devices = jax.devices()[:need]  # default pool: take what we need
    if len(devices) != need:
        raise ValueError(
            "need exactly %d devices for a %dx%d mesh, have %d"
            % (need, n_hosts, chips_per_host, len(devices))
        )
    grid = np.asarray(devices).reshape(n_hosts, chips_per_host)
    return Mesh(grid, tuple(axes))


def _node_axis(mesh: Mesh):
    """The PartitionSpec axis entry sharding the node dimension: the mesh's
    single axis name, or the tuple of all axes for multi-D meshes."""
    names = mesh.axis_names
    return names[0] if len(names) == 1 else tuple(names)


def _spec_for(x, axis) -> P:
    """Shard the leading (observer/node) axis; replicate scalars."""
    if getattr(x, "ndim", 0) == 0:
        return P()
    return P(axis, *([None] * (x.ndim - 1)))


def state_shardings(mesh: Mesh, state: engine.SimState):
    """NamedSharding pytree for a SimState: node axis sharded, rest local."""
    axis = _node_axis(mesh)
    return jax.tree.map(
        lambda x: NamedSharding(mesh, _spec_for(x, axis)), state
    )


def inputs_shardings(mesh: Mesh, inputs: engine.TickInputs):
    axis = _node_axis(mesh)
    return jax.tree.map(
        lambda x: NamedSharding(mesh, _spec_for(x, axis)), inputs
    )


def shard_state(state: engine.SimState, mesh: Mesh) -> engine.SimState:
    """Place a SimState onto the mesh with the node axis distributed."""
    return jax.device_put(state, state_shardings(mesh, state))


def _abstract_state(params: engine.SimParams, universe=None):
    """Shape-only SimState (no arrays built) for deriving shardings.
    Unfused checksum modes share one shape, so evaluate in fast mode —
    the farmhash mode requires a universe to seed the checksum cache,
    which a shape probe neither has nor needs.  Fused mode DOES change
    the state shape (the [N, N, R] record cache, R universe-dependent),
    so it traces the real init with the universe."""
    if params.fused_checksum == "on" and universe is not None:
        return jax.eval_shape(
            lambda: engine.init_state(params, universe=universe)
        )
    shape_params = params._replace(
        checksum_mode="fast", fused_checksum="off"
    )
    return jax.eval_shape(lambda: engine.init_state(shape_params))


def _replicated_metrics(mesh: Mesh):
    fields = len(engine.TickMetrics._fields)
    return jax.tree.map(
        lambda _: NamedSharding(mesh, P()), engine.TickMetrics(*[0] * fields)
    )


@functools.lru_cache(maxsize=None)
def make_sharded_tick(
    params: engine.SimParams, universe: ce.Universe, mesh: Mesh
):
    """Compile ``engine.tick`` as one SPMD program over the mesh.

    Returns ``f(state, inputs) -> (state, metrics)`` with state kept
    device-resident and node-sharded across ticks.  lru_cached on the
    (hashable) params/universe/mesh triple, like the single-device
    drivers: fresh ShardedSim instances with the same config reuse the
    compiled executable instead of re-tracing.
    """
    st_sh = state_shardings(mesh, _abstract_state(params, universe))
    in_sh = inputs_shardings(mesh, engine.TickInputs.quiet(params.n))
    metrics_sh = _replicated_metrics(mesh)
    fn = functools.partial(engine.tick, params=params, universe=universe)
    return jax.jit(
        fn, in_shardings=(st_sh, in_sh), out_shardings=(st_sh, metrics_sh)
    )


@functools.lru_cache(maxsize=None)
def make_sharded_scan(
    params: engine.SimParams, universe: ce.Universe, mesh: Mesh
):
    """Compile a ``lax.scan`` of the tick over a [T, N] event schedule.
    lru_cached like :func:`make_sharded_tick`."""
    st_sh = state_shardings(mesh, _abstract_state(params, universe))
    axis = _node_axis(mesh)
    sched_sh = jax.tree.map(
        lambda x: NamedSharding(mesh, P(None, axis)),
        engine.TickInputs.quiet(params.n),
    )
    metrics_sh = _replicated_metrics(mesh)

    def scanned(state, inputs):
        def body(st, inp):
            return engine.tick(st, inp, params, universe)

        return jax.lax.scan(body, state, inputs)

    return jax.jit(
        scanned,
        in_shardings=(st_sh, sched_sh),
        out_shardings=(st_sh, metrics_sh),
    )


def clear_executable_cache() -> None:
    """Drop the shared compiled SPMD executables (sweep hygiene, like the
    single-device drivers' clear hooks)."""
    make_sharded_tick.cache_clear()
    make_sharded_scan.cache_clear()
    _storm_tick_fn.cache_clear()
    _storm_scan_fn.cache_clear()


class ShardedSim(CheckpointableMixin):
    """A SimCluster-shaped driver whose state lives sharded on the mesh.

    The multi-chip twin of :class:`ringpop_tpu.models.sim.cluster.SimCluster`:
    same bootstrap/step/run surface, but every array carries a NamedSharding
    and the compiled tick is one SPMD program across all devices.
    """

    def __init__(
        self,
        n: int,
        mesh: Optional[Mesh] = None,
        params: Optional[engine.SimParams] = None,
        addresses: Optional[Sequence[str]] = None,
        seed: int = 0,
    ):
        from ringpop_tpu.models.sim.cluster import default_addresses

        self.mesh = mesh if mesh is not None else make_mesh()
        if addresses is None:
            addresses = default_addresses(n)
        self.universe = ce.Universe.from_addresses(addresses)
        self.params = params or engine.SimParams(n=self.universe.n)
        # pin trace-env-dependent params (hash_impl="env",
        # parity_recompute="auto") to concrete values, exactly like
        # SimCluster: the shared executable caches below key on params,
        # and a trace-time env read would serve stale lowerings across
        # RINGPOP_TPU_PALLAS toggles
        from ringpop_tpu.models.sim.cluster import _resolve_hash_impl

        self.params = _resolve_hash_impl(self.params)
        if self.params.n % self.mesh.devices.size:
            raise ValueError(
                "n=%d not divisible by mesh size %d"
                % (self.params.n, self.mesh.devices.size)
            )
        self.state = shard_state(
            engine.init_state(self.params, seed=seed, universe=self.universe),
            self.mesh,
        )
        self._tick = make_sharded_tick(self.params, self.universe, self.mesh)
        self._scan = make_sharded_scan(self.params, self.universe, self.mesh)
        # count of bounded-parity overflow replays, like SimCluster's — a
        # window that replayed paid the exact-shape cost too
        self.parity_replays = 0

    def bootstrap(self):
        inputs = engine.TickInputs.quiet(self.params.n)._replace(
            join=jnp.ones(self.params.n, bool)
        )
        return self.step(inputs)

    def _exact_params(self) -> engine.SimParams:
        """Exact-recompute twin for bounded-parity overflow replays (same
        contract as SimCluster's — see engine.SimParams.parity_recompute;
        fused runs always replay under "full")."""
        return self.params._replace(
            parity_recompute=engine.resolve_exact_recompute(
                self.params, jax.default_backend()
            )
        )

    def _maybe_replay_exact(self, pre, metrics, make_fn, inputs):
        """Bounded-parity overflow fallback, shared by step/run: discard
        the overflowed result and replay from the pre-run state under the
        exact twin program (same contract as SimCluster's)."""
        bounded = (
            self.params.checksum_mode == "farmhash"
            and self.params.parity_recompute == "bounded"
        )
        if not bounded or not int(np.asarray(metrics.parity_overflow).sum()):
            return None
        self.parity_replays += 1
        return make_fn(self._exact_params(), self.universe, self.mesh)(
            pre, inputs
        )

    def step(self, inputs: Optional[engine.TickInputs] = None):
        if inputs is None:
            inputs = engine.TickInputs.quiet(self.params.n)
        pre = self.state
        self.state, metrics = self._tick(pre, inputs)
        replayed = self._maybe_replay_exact(
            pre, metrics, make_sharded_tick, inputs
        )
        if replayed is not None:
            self.state, metrics = replayed
        self._after_ticks(1)
        return jax.tree.map(np.asarray, metrics)

    def run(self, schedule) -> engine.TickMetrics:
        return self._run_chunked(schedule, self._run_window)

    def _run_window(self, schedule) -> engine.TickMetrics:
        inputs = schedule.as_inputs()
        pre = self.state
        self.state, metrics = self._scan(pre, inputs)
        replayed = self._maybe_replay_exact(
            pre, metrics, make_sharded_scan, inputs
        )
        if replayed is not None:
            self.state, metrics = replayed
        return jax.tree.map(np.asarray, metrics)

    def checksums(self) -> np.ndarray:
        return np.asarray(self.state.checksum)

    # -- checkpoint/resume (models/sim/recovery.py) -----------------------
    # Saves gather the node-sharded state to host and split it across
    # per-shard files (default: one per mesh device); loads reassemble
    # full arrays and re-place them under THIS mesh's shardings, so a
    # checkpoint restores onto any device count — including down to the
    # single-device SimCluster (tests/parallel/test_sharded_ckpt.py).

    def _default_ckpt_shards(self) -> int:
        return int(self.mesh.devices.size)

    def _ckpt_spec(self) -> CheckpointSpec:
        return CheckpointSpec(
            engine.SimState, self.params, self._ckpt_sharded_fields()
        )

    def _ckpt_states(self):
        # live (sharded) state: the manager/save layer makes the ONE
        # host copy (recovery.host_copy_states) — copying here too would
        # memcpy the full state twice per cadence save
        return self.state

    def _ckpt_sharded_fields(self) -> frozenset:
        # every non-scalar SimState field is node-leading (_spec_for)
        return frozenset(
            f
            for f in self.state._fields
            if getattr(getattr(self.state, f), "ndim", 0) >= 1
        )

    def _ckpt_install(self, state) -> None:
        from ringpop_tpu.models.sim.cluster import fixup_sim_state

        self.state = shard_state(
            fixup_sim_state(state, self.params, self.universe), self.mesh
        )

    def save(self, path: str, shards: Optional[int] = None) -> None:
        """Manifest-format checkpoint directory at ``path``."""
        from ringpop_tpu.models.sim import checkpoint as ckpt
        from ringpop_tpu.models.sim.recovery import host_copy_states

        ckpt.save_checkpoint(
            path,
            host_copy_states(self.state),
            self.params,
            shards=self._default_ckpt_shards() if shards is None else shards,
            sharded_fields=self._ckpt_sharded_fields(),
        )

    def load(self, path: str) -> None:
        """Resume from ``path`` — a legacy ``.npz`` file or a manifest
        checkpoint directory (any shard count) alike."""
        from ringpop_tpu.models.sim import checkpoint as ckpt

        self._ckpt_install(
            ckpt.load_any(path, engine.SimState, self.params)
        )


# ---------------------------------------------------------------------------
# Scalable (rumor-table) engine over the mesh — the 1M-on-v5e-8 path.
# Node-indexed arrays shard over the mesh; the bounded rumor table, rng,
# and base_sum are tiny and replicate.  The gossip exchange's permutation
# gathers become all-to-alls over ICI; the limb-matmul checksum shards by
# rows with the [U, 4] limb table replicated.
# ---------------------------------------------------------------------------


# node-indexed ScalableState fields (sharded); everything else — the
# bounded [U] rumor table, the scalar clock/base, the rng — replicates.
# Single source: engine_scalable.NODE_SHARDED_FIELDS (shared with the
# sharded checkpoint split, models/sim/recovery.py)
from ringpop_tpu.models.sim.engine_scalable import (  # noqa: E402
    NODE_SHARDED_FIELDS as _SCALABLE_NODE_FIELDS,
)


def scalable_state_shardings(mesh: Mesh, params):
    from ringpop_tpu.models.sim import engine_scalable as es

    axis = _node_axis(mesh)
    abstract = jax.eval_shape(lambda: es.init_state(params))
    return type(abstract)(
        **{
            f: NamedSharding(
                mesh,
                P(axis, *([None] * (getattr(abstract, f).ndim - 1)))
                if f in _SCALABLE_NODE_FIELDS
                else P(),
            )
            for f in abstract._fields
        }
    )


def _storm_input_shardings(mesh, inputs, leading_time_axis: bool):
    axis = _node_axis(mesh)
    spec = P(None, axis) if leading_time_axis else P(axis)
    return jax.tree.map(lambda _: NamedSharding(mesh, spec), inputs)


def _storm_metrics_shardings(mesh):
    from ringpop_tpu.models.sim import engine_scalable as es

    m_fields = len(es.ScalableMetrics._fields)
    return jax.tree.map(
        lambda _: NamedSharding(mesh, P()),
        es.ScalableMetrics(*[0] * m_fields),
    )


def _storm_sample_inputs(n: int, structure_key):
    """A ChurnInputs pytree with the same STRUCTURE as the caller's (the
    optional partition/leave fields change the arg tree)."""
    import jax.numpy as _jnp

    from ringpop_tpu.models.sim import engine_scalable as es

    no_partition, no_leave = structure_key
    inputs = es.ChurnInputs.quiet(n)
    if not no_partition:
        inputs = inputs._replace(partition=_jnp.zeros(n, _jnp.int32))
    if not no_leave:
        inputs = inputs._replace(leave=_jnp.zeros(n, bool))
    return inputs


@functools.lru_cache(maxsize=None)
def _storm_tick_fn(params, mesh: Mesh, structure_key):
    from ringpop_tpu.models.sim import engine_scalable as es

    st_sh = scalable_state_shardings(mesh, params)
    in_sh = _storm_input_shardings(
        mesh, _storm_sample_inputs(params.n, structure_key), False
    )
    return jax.jit(
        functools.partial(es.tick, params=params),
        in_shardings=(st_sh, in_sh),
        out_shardings=(st_sh, _storm_metrics_shardings(mesh)),
    )


@functools.lru_cache(maxsize=None)
def _storm_scan_fn(params, mesh: Mesh, structure_key):
    from ringpop_tpu.models.sim import engine_scalable as es

    st_sh = scalable_state_shardings(mesh, params)
    in_sh = _storm_input_shardings(
        mesh, _storm_sample_inputs(params.n, structure_key), True
    )

    def scanned(state, inp):
        def body(st, i):
            return es.tick(st, i, params)

        return jax.lax.scan(body, state, inp)

    return jax.jit(
        scanned,
        in_shardings=(st_sh, in_sh),
        out_shardings=(st_sh, _storm_metrics_shardings(mesh)),
    )


class ShardedStorm(CheckpointableMixin):
    """ScalableCluster over a device mesh: one SPMD program per tick/scan.

    The driver behind the 1M churn-storm north-star's v5e-8 configuration:
    same step/run surface as
    :class:`ringpop_tpu.models.sim.storm.ScalableCluster`, with every
    node-indexed array ``P("nodes")``-sharded and the trajectory bitwise
    equal to the single-device engine (tests/parallel/test_mesh.py)."""

    def __init__(self, n, mesh=None, params=None, seed: int = 0):
        from ringpop_tpu.models.sim import engine_scalable as es

        self.mesh = mesh if mesh is not None else make_mesh()
        self.params = params or es.ScalableParams(n=n)
        if self.params.n != n:
            self.params = self.params._replace(n=n)
        # pin trace-time "auto" knobs exactly like ScalableCluster: the
        # module-level executable caches key on params, and the SPMD
        # trajectory must stay bitwise equal to the single-device engine
        # regardless of which backend resolved first.  One mesh-specific
        # override: an auto-resolved "pallas" exchange drops to the
        # bit-exact XLA twin — a pallas_call does not partition under
        # the sharded pjit (GSPMD can't see inside the kernel), while
        # the twin's vector ops shard by rows like the rest of the tick.
        # An EXPLICIT "pallas" is honored (replicated kernel: correct,
        # measurably slower — the A/B knob for the chip session).
        self.params = es.resolve_scalable_params(
            self.params, jax.default_backend()
        )
        if (
            (params is None or params.fused_exchange == "auto")
            and self.params.fused_exchange == "pallas"
        ):
            self.params = self.params._replace(fused_exchange="xla")
        if n % self.mesh.devices.size:
            raise ValueError(
                "n=%d not divisible by mesh size %d"
                % (n, self.mesh.devices.size)
            )
        self._st_sh = scalable_state_shardings(self.mesh, self.params)
        self.state = jax.device_put(
            es.init_state(self.params, seed=seed), self._st_sh
        )
        # jitted fns are resolved per input-pytree structure (ChurnInputs'
        # optional partition/leave change the arg tree) from MODULE-LEVEL
        # caches shared across instances, like the single-device drivers

    def _structure_key(self, inputs):
        return (inputs.partition is None, inputs.leave is None)

    def step(self, inputs=None):
        from ringpop_tpu.models.sim import engine_scalable as es

        if inputs is None:
            inputs = es.ChurnInputs.quiet(self.params.n)
        tick = _storm_tick_fn(
            self.params, self.mesh, self._structure_key(inputs)
        )
        self.state, m = tick(self.state, inputs)
        self._after_ticks(1)
        return jax.tree.map(np.asarray, m)

    def run(self, schedule):
        return self._run_chunked(schedule, self._run_window)

    def _run_window(self, schedule):
        inputs = schedule.as_inputs()
        scan = _storm_scan_fn(
            self.params, self.mesh, self._structure_key(inputs)
        )
        self.state, ms = scan(self.state, inputs)
        return jax.tree.map(np.asarray, ms)

    def checksums(self) -> np.ndarray:
        from ringpop_tpu.models.sim import engine_scalable as es

        if not bool(self.params.checksum_in_tick):
            return np.asarray(es.compute_checksums(self.state, self.params))
        return np.asarray(self.state.checksum)

    # -- checkpoint/resume (models/sim/recovery.py) -----------------------
    # Node-sharded fields (engine_scalable.NODE_SHARDED_FIELDS) split
    # across per-shard files — one per mesh device by default; the rumor
    # table/rng/base replicate into the common file.  Restores reassemble
    # and re-place under THIS mesh's shardings, so a 8-shard save resumes
    # on any device count (bitwise vs the single-file path — the gate in
    # tests/parallel/test_sharded_ckpt.py).

    def _default_ckpt_shards(self) -> int:
        return int(self.mesh.devices.size)

    def _ckpt_spec(self) -> CheckpointSpec:
        from ringpop_tpu.models.sim import engine_scalable as es

        return CheckpointSpec(
            es.ScalableState, self.params, es.NODE_SHARDED_FIELDS
        )

    def _ckpt_states(self):
        # live state; the save layer makes the one host copy
        return self.state

    def _ckpt_install(self, state) -> None:
        from ringpop_tpu.models.sim.storm import fixup_scalable_state

        self.state = jax.device_put(
            fixup_scalable_state(state, self.params), self._st_sh
        )

    def save(self, path: str, shards: Optional[int] = None) -> None:
        """Manifest-format checkpoint directory at ``path``."""
        from ringpop_tpu.models.sim import checkpoint as ckpt
        from ringpop_tpu.models.sim import engine_scalable as es
        from ringpop_tpu.models.sim.recovery import host_copy_states

        ckpt.save_checkpoint(
            path,
            host_copy_states(self.state),
            self.params,
            shards=self._default_ckpt_shards() if shards is None else shards,
            sharded_fields=es.NODE_SHARDED_FIELDS,
        )

    def load(self, path: str) -> None:
        """Resume from ``path`` — a legacy ``.npz`` file or a manifest
        checkpoint directory (any shard count) alike."""
        from ringpop_tpu.models.sim import checkpoint as ckpt
        from ringpop_tpu.models.sim import engine_scalable as es

        self._ckpt_install(
            ckpt.load_any(path, es.ScalableState, self.params)
        )
