"""Device-mesh parallelism: sharding the N-node axis over TPU chips."""

from ringpop_tpu.parallel.mesh import (
    make_mesh,
    state_shardings,
    inputs_shardings,
    shard_state,
    make_sharded_tick,
    ShardedSim,
    clear_executable_cache,
)

__all__ = [
    "make_mesh",
    "state_shardings",
    "inputs_shardings",
    "shard_state",
    "make_sharded_tick",
    "ShardedSim",
    "clear_executable_cache",
]
