"""Host-side networking: the framed JSON-over-TCP channel (the framework's
transport layer, playing the role TChannel plays for the reference) and the
timer service behind gossip/suspicion/proxy scheduling."""

from ringpop_tpu.net.channel import Channel, ChannelError, RemoteError
from ringpop_tpu.net.timers import FakeTimers, Timers

__all__ = ["Channel", "ChannelError", "RemoteError", "Timers", "FakeTimers"]
