"""Framed JSON-over-TCP RPC channel — the framework's transport layer.

Plays the role TChannel plays for the reference (SURVEY.md §5.8): endpoints
registered by name, requests carrying ``(head, body)`` JSON payloads,
per-request timeouts, out-of-order responses over a persistent connection.
The usage surface mirrors how ringpop drives TChannel — ``register(endpoint,
handler)`` (server/index.js:28-37) and ``request(...).send(endpoint, head,
body, cb)`` (lib/gossip/ping-sender.js:81-98) — without porting TChannel's
frame format: the wire is length-prefixed JSON, which is sufficient for the
protocol bodies (all of ringpop's bodies are JSON strings already).

Wire format: 4-byte big-endian length, then a JSON object
``{id, type: "req"|"res", endpoint?, head, body, ok?, error?}``.

Threading model: one acceptor thread + one reader thread per connection
(inbound and outbound).  Requests block the calling thread until response or
timeout — gossip runs on its own thread, mirroring the event-loop's
"one protocol period in flight" behavior (gossip/index.js isPinging guard).
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Any, Callable, Dict, Optional, Tuple

_LEN = struct.Struct(">I")
MAX_FRAME = 64 * 1024 * 1024


class ChannelError(Exception):
    """Transport-level failure (connect/timeout/closed)."""

    def __init__(self, message: str, type_: str = "ringpop-tpu.channel"):
        super().__init__(message)
        self.type = type_


class RemoteError(Exception):
    """The remote handler answered with an application error."""

    def __init__(self, payload: Any):
        super().__init__(str(payload))
        self.payload = payload


Handler = Callable[[Any, Any], Tuple[Any, Any]]


class _Conn:
    """A persistent framed connection with response correlation."""

    def __init__(self, sock: socket.socket, channel: "Channel"):
        self.sock = sock
        self.channel = channel
        self.send_lock = threading.Lock()
        self.pending: Dict[int, "threading.Event"] = {}
        self.responses: Dict[int, dict] = {}
        self.lock = threading.Lock()
        self.closed = False
        self.reader = threading.Thread(target=self._read_loop, daemon=True)
        self.reader.start()

    def _read_loop(self) -> None:
        try:
            buf = b""
            while True:
                while len(buf) < 4:
                    chunk = self.sock.recv(65536)
                    if not chunk:
                        raise ConnectionError("closed")
                    buf += chunk
                (length,) = _LEN.unpack(buf[:4])
                if length > MAX_FRAME:
                    raise ConnectionError("oversized frame")
                buf = buf[4:]
                while len(buf) < length:
                    chunk = self.sock.recv(65536)
                    if not chunk:
                        raise ConnectionError("closed")
                    buf += chunk
                frame, buf = buf[:length], buf[length:]
                msg = json.loads(frame.decode("utf-8"))
                if msg.get("type") == "req":
                    threading.Thread(
                        target=self.channel._dispatch,
                        args=(self, msg),
                        daemon=True,
                    ).start()
                else:
                    with self.lock:
                        ev = self.pending.get(msg.get("id"))
                        if ev is not None:
                            self.responses[msg["id"]] = msg
                            ev.set()
        except (OSError, ConnectionError, ValueError):
            self.close()

    def send_msg(self, msg: dict) -> None:
        data = json.dumps(msg).encode("utf-8")
        with self.send_lock:
            self.sock.sendall(_LEN.pack(len(data)) + data)

    def call(self, msg: dict, timeout_s: float) -> dict:
        ev = threading.Event()
        with self.lock:
            if self.closed:
                raise ChannelError("connection closed")
            self.pending[msg["id"]] = ev
        try:
            self.send_msg(msg)
            if not ev.wait(timeout_s):
                raise ChannelError(
                    "timed out after %.1fs" % timeout_s, "ringpop-tpu.timeout"
                )
            with self.lock:
                res = self.responses.pop(msg["id"], None)
            if res is None:
                raise ChannelError("connection closed mid-request")
            return res
        finally:
            with self.lock:
                self.pending.pop(msg["id"], None)

    def close(self) -> None:
        with self.lock:
            if self.closed:
                return
            self.closed = True
            events = list(self.pending.values())
        try:
            self.sock.close()
        except OSError:
            pass
        for ev in events:
            ev.set()
        self.channel._forget(self)


class Channel:
    """A listening endpoint registry + outbound request pool."""

    def __init__(self, host_port: Optional[str] = None):
        self.host_port = host_port
        self.handlers: Dict[str, Handler] = {}
        self._server_sock: Optional[socket.socket] = None
        self._conns: Dict[str, _Conn] = {}
        self._inbound: list = []
        self._lock = threading.Lock()
        self._next_id = 0
        self._id_lock = threading.Lock()
        self.destroyed = False

    # -- server side ------------------------------------------------------

    def register(self, endpoint: str, handler: Handler) -> None:
        """``handler(head, body) -> (res_head, res_body)``; raise
        RemoteError(payload) (or any exception) to answer with an error."""
        self.handlers[endpoint] = handler

    def listen(self) -> str:
        host, _, port = (self.host_port or "127.0.0.1:0").rpartition(":")
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        s.bind((host or "127.0.0.1", int(port)))
        s.listen(128)
        self._server_sock = s
        self.host_port = "%s:%d" % (host or "127.0.0.1", s.getsockname()[1])
        t = threading.Thread(target=self._accept_loop, daemon=True)
        t.start()
        return self.host_port

    def _accept_loop(self) -> None:
        try:
            while True:
                sock, _ = self._server_sock.accept()
                # destroy() may have raced with the blocking accept(2): the
                # kernel listener completes handshakes until the acceptor
                # wakes, so a "dead" node must refuse, not serve
                if self.destroyed:
                    sock.close()
                    return
                sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                with self._lock:
                    self._inbound.append(_Conn(sock, self))
        except OSError:
            pass

    def _dispatch(self, conn: _Conn, msg: dict) -> None:
        if self.destroyed:
            conn.close()
            return
        endpoint = msg.get("endpoint")
        handler = self.handlers.get(endpoint)
        res = {"id": msg["id"], "type": "res"}
        if handler is None:
            res.update(ok=False, error={"type": "ringpop-tpu.bad-endpoint",
                                        "message": "no handler for %r" % endpoint})
        else:
            try:
                head, body = handler(msg.get("head"), msg.get("body"))
                res.update(ok=True, head=head, body=body)
            except RemoteError as e:
                res.update(ok=False, error=e.payload)
            except Exception as e:  # handler bug -> structured error
                res.update(
                    ok=False,
                    error={"type": "ringpop-tpu.handler-error", "message": str(e)},
                )
        try:
            conn.send_msg(res)
        except OSError:
            conn.close()

    # -- client side ------------------------------------------------------

    def _conn_to(self, host_port: str) -> _Conn:
        with self._lock:
            conn = self._conns.get(host_port)
            if conn is not None and not conn.closed:
                return conn
        host, _, port = host_port.rpartition(":")
        sock = socket.create_connection((host, int(port)), timeout=5.0)
        # TCP self-connect guard: connecting to a dead peer's (ephemeral)
        # port can pick that very port as the SOURCE and connect the socket
        # to itself — the "peer" then answers with OUR handlers, e.g. a
        # destroyed node appearing to answer pings.  Treat as dead peer.
        if sock.getsockname() == sock.getpeername():
            sock.close()
            raise ConnectionRefusedError(
                "self-connection to %s (peer is down)" % host_port
            )
        sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        sock.settimeout(None)
        conn = _Conn(sock, self)
        with self._lock:
            existing = self._conns.get(host_port)
            if existing is not None and not existing.closed:
                conn.close()
                return existing
            self._conns[host_port] = conn
        return conn

    def _forget(self, conn: _Conn) -> None:
        with self._lock:
            for k, v in list(self._conns.items()):
                if v is conn:
                    del self._conns[k]
            if conn in self._inbound:
                self._inbound.remove(conn)

    def request(
        self,
        host_port: str,
        endpoint: str,
        head: Any = None,
        body: Any = None,
        timeout_s: float = 5.0,
    ) -> Tuple[Any, Any]:
        """Send one request; returns ``(head, body)`` or raises
        ChannelError / RemoteError."""
        if self.destroyed:
            raise ChannelError("channel destroyed")
        with self._id_lock:
            self._next_id += 1
            rid = self._next_id
        msg = {
            "id": rid,
            "type": "req",
            "endpoint": endpoint,
            "head": head,
            "body": body,
        }
        try:
            conn = self._conn_to(host_port)
            res = conn.call(msg, timeout_s)
        except (OSError, ConnectionError) as e:
            raise ChannelError("connect to %s failed: %s" % (host_port, e))
        if not res.get("ok"):
            raise RemoteError(res.get("error"))
        return res.get("head"), res.get("body")

    def destroy(self) -> None:
        self.destroyed = True
        if self._server_sock is not None:
            try:
                # shutdown wakes an acceptor blocked in accept(2); closing
                # alone leaves the kernel listener accepting into the
                # backlog while the thread sleeps (a destroyed node would
                # keep answering pings and refute its own suspicion)
                self._server_sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                self._server_sock.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns.values()) + list(self._inbound)
        for c in conns:
            c.close()
