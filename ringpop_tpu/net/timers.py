"""Timer service: schedulable delayed callbacks with a fake-time twin.

The reference leans on the Node event loop's ``setTimeout`` for every
protocol clock — gossip periods (lib/gossip/index.js:68), suspicion timers
(lib/gossip/suspicion.js:58-76), proxy retry schedules (lib/request-proxy/
send.js:210-228) — and its tests swap in mock timers to advance time by hand
(test/lib/alloc-ringpop.js:24-63 wires time-mock).  This module is the same
split: ``Timers`` drives real ``threading.Timer`` objects; ``FakeTimers``
holds a virtual clock that tests step with ``advance()``.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, List, Optional, Tuple


class Timers:
    """Real timers backed by ``threading.Timer``."""

    def set_timeout(self, fn: Callable[[], None], delay_s: float):
        t = threading.Timer(delay_s, fn)
        t.daemon = True
        t.start()
        return t

    def clear_timeout(self, handle) -> None:
        if handle is not None:
            handle.cancel()

    def now_ms(self) -> int:
        return int(time.time() * 1000)

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


class FakeTimers(Timers):
    """Virtual clock; pending callbacks fire on ``advance()``."""

    def __init__(self, start_ms: int = 1414142122274):
        self._now_ms = start_ms
        self._pending: List[Tuple[float, int, Callable[[], None]]] = []
        self._seq = 0
        self._lock = threading.Lock()

    def set_timeout(self, fn: Callable[[], None], delay_s: float):
        with self._lock:
            self._seq += 1
            entry = (self._now_ms + delay_s * 1000.0, self._seq, fn)
            self._pending.append(entry)
            return entry

    def clear_timeout(self, handle) -> None:
        with self._lock:
            try:
                self._pending.remove(handle)
            except ValueError:
                pass

    def now_ms(self) -> int:
        return int(self._now_ms)

    def sleep(self, seconds: float) -> None:
        self.advance(seconds)

    def advance(self, seconds: float) -> int:
        """Move the clock forward, firing due callbacks in time order.
        Returns the number of callbacks fired."""
        target = self._now_ms + seconds * 1000.0
        fired = 0
        while True:
            with self._lock:
                due = [e for e in self._pending if e[0] <= target]
                if not due:
                    self._now_ms = target
                    return fired
                due.sort(key=lambda e: (e[0], e[1]))
                entry = due[0]
                self._pending.remove(entry)
                self._now_ms = max(self._now_ms, entry[0])
            entry[2]()
            fired += 1
