"""jaxgate prong: static non-interference proof for the obs planes.

The repo's core correctness contract is *gate-equivalence neutrality*:
every observability plane — flight recorder (``ev_buf``/``ev_head``/
``ev_drops``), latency histograms (``hist``), rumor wavefronts
(``first_heard``) — and every per-tick metrics struct must be bitwise
invisible to the trajectory.  The n=64 tier-1 / n=1k slow A/B suites
*sample* that property dynamically; this prong PROVES the dataflow half
of it statically, per traced entry point:

    no obs-only input leaf reaches any trajectory output leaf.

Field classes come from ONE registry per engine
(``engine.SIM_TRAJECTORY_FIELDS`` / ``SIM_OBS_ONLY_FIELDS``,
``engine_scalable.SCALABLE_*``, ``plane.ROUTE_*`` — the repo-scan gate
tests/analysis/test_state_registry.py keeps them total and disjoint).
The entry points are the jaxpr prong's registry
(jaxpr_audit.DEFAULT_ENTRIES): each is traced, its flattened input
leaves labeled from the state registries, and the transitive def-use
slice (analysis/dataflow.py, loop carries to a fixpoint) is checked —
an obs leaf reaching a trajectory leaf is a finding that names the
offending equation chain.

Metrics structs (``*Metrics``) are classified as observability SINKS:
obs state may flow into them.  They are still trajectory-DERIVED in the
dynamic gates (bitwise-compared across obs on/off), so a mask that
starts reading an obs plane shows up there; what this prong pins is the
state-to-state dataflow the PR-7/PR-8 class of bug lives in.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ringpop_tpu.analysis import dataflow
from ringpop_tpu.analysis.findings import Finding

# kinds a leaf can carry
KIND_TRAJ = "trajectory"
KIND_OBS = "obs-only"
KIND_METRICS = "metrics"
KIND_OTHER = "other"
KIND_UNCLASSIFIED = "unclassified"


@dataclasses.dataclass(frozen=True)
class Label:
    kind: str
    path: str  # e.g. "SimState.hist" or "arg1"


def state_registries() -> Dict[str, Tuple[frozenset, frozenset]]:
    """class name -> (trajectory fields, obs-only fields); the single
    sources live next to the state classes themselves."""
    from ringpop_tpu.models.route import plane
    from ringpop_tpu.models.sim import engine, engine_scalable as es

    return {
        "SimState": (
            engine.SIM_TRAJECTORY_FIELDS,
            engine.SIM_OBS_ONLY_FIELDS,
        ),
        "ScalableState": (
            es.SCALABLE_TRAJECTORY_FIELDS,
            es.SCALABLE_OBS_ONLY_FIELDS,
        ),
        "RouteState": (
            plane.ROUTE_TRAJECTORY_FIELDS,
            plane.ROUTE_OBS_ONLY_FIELDS,
        ),
    }


def _is_namedtuple(x) -> bool:
    return isinstance(x, tuple) and hasattr(x, "_fields")


def label_tree(x, regs: Dict[str, Tuple[frozenset, frozenset]], path: str,
               kind: str = KIND_OTHER):
    """Structure-identical pytree with a :class:`Label` at every leaf.

    Registered state classes label their fields from the registry;
    ``*Metrics`` namedtuples become metrics sinks; everything nested
    under a classified field inherits its class (a RingState inside
    ``RouteState.ring`` is trajectory)."""
    if x is None:
        return None
    if _is_namedtuple(x):
        cls = type(x).__name__
        if cls in regs:
            traj, obs = regs[cls]
            parts = []
            for f, v in zip(x._fields, x):
                if f in obs:
                    k = KIND_OBS
                elif f in traj:
                    k = KIND_TRAJ
                else:
                    k = KIND_UNCLASSIFIED
                parts.append(label_tree(v, regs, f"{cls}.{f}", k))
            return type(x)(*parts)
        sub_kind = KIND_METRICS if cls.endswith("Metrics") else kind
        return type(x)(
            *(
                label_tree(v, regs, f"{path or cls}.{f}", sub_kind)
                for f, v in zip(x._fields, x)
            )
        )
    if isinstance(x, (tuple, list)):
        return type(x)(
            label_tree(v, regs, f"{path}[{i}]", kind)
            for i, v in enumerate(x)
        )
    if isinstance(x, dict):
        return {
            k: label_tree(v, regs, f"{path}[{k!r}]", kind)
            for k, v in x.items()
        }
    return Label(kind, path)


def _flatten_labels(labels) -> List[Label]:
    import jax

    return jax.tree_util.tree_flatten(
        labels, is_leaf=lambda v: isinstance(v, Label)
    )[0]


def check_entry(
    name: str, fn, args: Tuple, cache_as: Optional[str] = None
) -> List[Finding]:
    """Prove non-interference for one traced entry point.

    ``cache_as`` names a REGISTERED entry whose trace may be shared with
    the jaxpr prong (jaxpr_audit.trace_entry) — ad-hoc callers (the
    mutation tests' doctored entries) leave it None and trace fresh."""
    import jax

    regs = state_registries()
    findings: List[Finding] = []
    in_labels = _flatten_labels(label_tree(tuple(args), regs, "args"))
    for lab in in_labels:
        if lab.kind == KIND_UNCLASSIFIED:
            findings.append(
                Finding(
                    rule="unclassified-state-field",
                    path=f"<entry:{name}>",
                    line=0,
                    message=(
                        f"state field {lab.path} is in neither the "
                        "trajectory nor the obs-only registry — classify "
                        "it next to the state class (see "
                        "engine.SIM_TRAJECTORY_FIELDS)"
                    ),
                    prong="noninterference",
                )
            )
    if not any(lab.kind == KIND_OBS for lab in in_labels):
        return findings  # nothing to prove: no obs plane in this entry

    try:
        if cache_as is not None:
            from ringpop_tpu.analysis import jaxpr_audit as ja

            closed, out_shape = ja.trace_entry(cache_as, fn, args)
        else:
            closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(
                *args
            )
    except Exception as e:
        findings.append(
            Finding(
                rule="trace-failure",
                path=f"<entry:{name}>",
                line=0,
                message=(
                    f"entry point failed to trace: {type(e).__name__}: {e}"
                ),
                prong="noninterference",
            )
        )
        return findings

    if len(in_labels) != len(closed.jaxpr.invars):
        findings.append(
            Finding(
                rule="trace-failure",
                path=f"<entry:{name}>",
                line=0,
                message=(
                    f"label/trace mismatch: {len(in_labels)} labeled input "
                    f"leaves vs {len(closed.jaxpr.invars)} jaxpr inputs"
                ),
                prong="noninterference",
            )
        )
        return findings

    seeds = [
        lab.path if lab.kind == KIND_OBS else None for lab in in_labels
    ]
    reach = dataflow.slice_reachability(closed, seeds)
    out_labels = _flatten_labels(label_tree(out_shape, regs, "out"))
    if len(out_labels) != len(reach):
        findings.append(
            Finding(
                rule="trace-failure",
                path=f"<entry:{name}>",
                line=0,
                message=(
                    f"label/trace mismatch: {len(out_labels)} labeled "
                    f"output leaves vs {len(reach)} jaxpr outputs"
                ),
                prong="noninterference",
            )
        )
        return findings

    for out_lab, reached in zip(out_labels, reach):
        if out_lab.kind != KIND_TRAJ or not reached:
            continue
        for src, witness in sorted(reached.items()):
            findings.append(
                Finding(
                    rule="obs-interference",
                    path=f"<entry:{name}>",
                    line=0,
                    message=(
                        f"obs-only input {src} reaches trajectory output "
                        f"{out_lab.path} — the observability plane leaks "
                        "into the gate-compared state; eqn chain: "
                        f"{dataflow.witness_chain(witness)}"
                    ),
                    prong="noninterference",
                )
            )
    return findings


# entry names that carry an obs plane at trace time — the tier-1
# cheap-gate subset and the default documentation set.  Entries outside
# this list are still scanned by a full run (they prove vacuous: no obs
# input leaves), so a NEW obs-carrying entry is picked up automatically.
OBS_ENTRY_NAMES: Tuple[str, ...] = (
    "engine-tick-scan-flight-recorder",
    "engine-tick-scan-histograms",
    "engine-scalable-tick-wavefront",
    "engine-scalable-tick-histograms",
    "route-tick-histograms",
    # round-19 request observatory: RouteState.req_* (sampled trace
    # buffer + sampled-subset counters) are obs-only — the prong proves
    # neither the records nor the counts reach the gate-compared state
    "route-tick-reqtrace",
    "fuzz-scenario-scan-full",
    # round-17 mesh observatory: ScalableState.exch/exch_hist are
    # obs-only — both the shard_map'd plane shape and the single-device
    # analytic twin must prove the counter planes never reach the
    # trajectory.  (exchange-plane-metrics itself takes bare arrays, no
    # registered state class, so it proves vacuously and stays out.)
    "engine-scalable-tick-shardmap-metrics",
    "engine-scalable-tick-exchange-metrics",
)

# module suffixes feeding each obs-carrying entry — the --changed-only
# touched-module -> affected-entry-point mapping (satellite: a scoped
# run only re-proves the entries a changed module can influence; any
# change under analysis/ re-proves everything).
ENTRY_SOURCES: Dict[str, Tuple[str, ...]] = {
    "engine-tick-scan-flight-recorder": (
        "models/sim/engine.py",
        "models/sim/flight.py",
        "models/sim/gating.py",
        "ops/",
    ),
    "engine-tick-scan-histograms": (
        "models/sim/engine.py",
        "models/sim/gating.py",
        "ops/",
    ),
    "engine-scalable-tick-wavefront": (
        "models/sim/engine_scalable.py",
        "ops/",
    ),
    "engine-scalable-tick-histograms": (
        "models/sim/engine_scalable.py",
        "ops/",
    ),
    "route-tick-histograms": ("models/route/", "ops/"),
    "route-tick-reqtrace": ("models/route/", "ops/"),
    "engine-scalable-tick-shardmap-metrics": (
        "models/sim/engine_scalable.py",
        "parallel/mesh.py",
        "ops/",
    ),
    "engine-scalable-tick-exchange-metrics": (
        "models/sim/engine_scalable.py",
        "ops/",
    ),
    "fuzz-scenario-scan-full": (
        "models/sim/engine.py",
        "models/sim/flight.py",
        "models/sim/gating.py",
        "fuzz/executor.py",
        "ops/",
    ),
}

# any touched file here re-proves every entry (the analysis itself or a
# state registry changed)
GLOBAL_SOURCES: Tuple[str, ...] = (
    "analysis/",
    "models/sim/engine.py",
    "models/sim/engine_scalable.py",
    "models/route/plane.py",
)


def entries_for_changed(rel_paths: Iterable[str]) -> List[str]:
    """Affected entry names for a set of changed package-relative paths
    (e.g. ``models/sim/flight.py``).  Empty list = prong can be skipped."""
    rels = list(rel_paths)
    if any(r.startswith(GLOBAL_SOURCES) for r in rels):
        return list(OBS_ENTRY_NAMES)
    out = []
    for name, sources in ENTRY_SOURCES.items():
        if any(r.startswith(sources) for r in rels):
            out.append(name)
    return out


def check_noninterference(
    entry_names: Optional[Sequence[str]] = None,
) -> List[Finding]:
    """The prong: prove non-interference for the registered entries.

    ``entry_names=None`` scans the WHOLE jaxpr registry — entries with
    no obs input leaves prove vacuously without paying a trace.  A
    subset (tier-1 cheap gate, --changed-only) names entries explicitly.
    """
    from ringpop_tpu.analysis import jaxpr_audit as ja

    by_name = {ep.name: ep for ep in ja.DEFAULT_ENTRIES}
    names = (
        list(by_name) if entry_names is None else list(entry_names)
    )
    findings: List[Finding] = []
    for name in names:
        ep = by_name.get(name)
        if ep is None:
            findings.append(
                Finding(
                    rule="trace-failure",
                    path=f"<entry:{name}>",
                    line=0,
                    message="unknown entry point",
                    prong="noninterference",
                )
            )
            continue
        try:
            fn, args = ep.build()
        except Exception as e:
            findings.append(
                Finding(
                    rule="trace-failure",
                    path=f"<entry:{name}>",
                    line=0,
                    message=(
                        f"entry point setup failed: {type(e).__name__}: {e}"
                    ),
                    prong="noninterference",
                )
            )
            continue
        findings.extend(check_entry(name, fn, args, cache_as=name))
    return findings
