"""Finding model + suppression parsing shared by both jaxgate prongs.

A finding is one violation of a machine-checked invariant: the AST lint
(prong B) reports (rule, file, line); the jaxpr auditor and retrace-budget
probes (prong A) report (rule, entry-point, location-in-jaxpr).  Both are
rendered through the same text/json formatters so the CLI and CI test see
one stream.

Suppressions are line-scoped comments in the linted source::

    x = int(traced_thing)  # jaxgate: ignore[host-coerce]
    y = int(other_thing)   # jaxgate: ignore

``ignore[rule-a,rule-b]`` silences only the named rules on that physical
line; a bare ``ignore`` silences every rule.  A ``# jaxgate: host`` marker
on a ``def`` line excludes that function from jit-context inference (see
:mod:`ringpop_tpu.analysis.astlint`).
"""

from __future__ import annotations

import dataclasses
import io
import json
import re
import tokenize
from typing import Dict, Iterable, List, Optional, Set, Tuple

_SUPPRESS_RE = re.compile(
    r"#\s*jaxgate:\s*ignore(?:\[(?P<rules>[a-z0-9_,\- ]+)\])?"
)
_HOST_RE = re.compile(r"#\s*jaxgate:\s*host\b")


def _comment_lines(source: str) -> Dict[int, str]:
    """line -> comment text, from real COMMENT tokens only — a marker
    spelled inside a string literal or docstring is not a marker."""
    out: Dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        # untokenizable source: no suppressions at all — strictly safer
        # than a raw-line fallback that would honor markers inside string
        # literals (the lint separately reports these files as
        # syntax-error findings, so nothing is silently skipped)
        return {}
    return out


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # rule id, e.g. "host-coerce" or "callback-primitive"
    path: str  # repo-relative file, or "<entry:NAME>" for jaxpr findings
    line: int  # 1-based source line; 0 when not file-anchored
    message: str
    prong: str = "ast"  # "ast" | "jaxpr" | "retrace"
    source: str = ""  # offending source line, stripped (text context)
    end_line: int = 0  # last line of the offending node (0 = same as line)

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def sort_key(self) -> Tuple:
        return (self.prong, self.path, self.line, self.rule)


# suppression table: line -> None (all rules) or a set of rule ids
Suppressions = Dict[int, Optional[Set[str]]]


def parse_suppressions(source: str) -> Suppressions:
    table: Suppressions = {}
    for i, text in _comment_lines(source).items():
        m = _SUPPRESS_RE.search(text)
        if not m:
            continue
        rules = m.group("rules")
        if rules is None:
            table[i] = None
        else:
            table[i] = {r.strip() for r in rules.split(",") if r.strip()}
    return table


def host_marked_lines(source: str) -> Set[int]:
    """Lines carrying ``# jaxgate: host`` (function-level jit opt-out)."""
    return {
        i
        for i, text in _comment_lines(source).items()
        if _HOST_RE.search(text)
    }


def is_suppressed(f: Finding, table: Suppressions) -> bool:
    # a black-wrapped statement puts the comment on its LAST physical
    # line; honor a marker anywhere in the node's line span
    for line in range(f.line, max(f.line, f.end_line or f.line) + 1):
        if line in table:
            rules = table[line]
            if rules is None or f.rule in rules:
                return True
    return False


def render_text(findings: Iterable[Finding]) -> str:
    out: List[str] = []
    fs = sorted(findings, key=Finding.sort_key)
    for f in fs:
        loc = f"{f.path}:{f.line}" if f.line else f.path
        out.append(f"{loc}: [{f.prong}:{f.rule}] {f.message}")
        if f.source:
            out.append(f"    {f.source}")
    out.append(f"{len(fs)} finding(s)")
    return "\n".join(out)


def render_json(
    findings: Iterable[Finding], meta: Optional[dict] = None
) -> str:
    fs = sorted(findings, key=Finding.sort_key)
    doc = {"findings": [f.as_dict() for f in fs], "count": len(fs)}
    if meta:
        # extra top-level keys (e.g. the CLI's per-prong wall clocks);
        # findings/count always win on collision
        doc = {**meta, **doc}
    return json.dumps(doc, indent=2, sort_keys=True)
