"""jaxgate prong: interval-range overflow/index certification.

ISSUE 18's scale certifier consumer #1: run the interval-domain
abstract interpreter (:mod:`analysis.ranges`) over every registered
entry point (jaxpr_audit.DEFAULT_ENTRIES) and fail on any NEW way a
value range can escape its dtype under the declared scale contracts
(N up to 64Mi nodes, ticks up to 2^20, capacity envelopes in
``ranges.ENTRY_SCALES``).  Three rules:

``dtype-overflow``
    an equation whose result interval escapes its dtype from in-range
    inputs (including reduce_sum re-checked at the DECLARED N, and
    lossy convert_element_type);
``unbounded-carry``
    a signed scan/while carry whose widened fixpoint escapes its dtype
    — the per-tick-growing-counter class, named via the state-field
    labels from :mod:`analysis.noninterference`;
``index-overflow``
    an iota/gather/scatter/dynamic_slice index lane whose indexed
    extent exceeds the index dtype at the declared N ceiling.

The TRIAGED findings on the current tree live in :data:`ALLOWED`,
each with the justification that makes the wrap benign (or the
documented contract that bounds it).  The allowlist is exact-ish by
design: fnmatch patterns over (entry, rule:key), and a FULL run
reports any row that suppressed nothing as ``stale-allowlist`` so the
table can only shrink in step with the code.  Mutation tests doctor an
entry (seeded int32 accumulator) and assert the prong catches it; the
ad-hoc :func:`check_entry` mirrors noninterference's so they can.
"""

from __future__ import annotations

import dataclasses
import functools
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from ringpop_tpu.analysis import ranges
from ringpop_tpu.analysis.findings import Finding


@dataclasses.dataclass(frozen=True)
class AllowRow:
    """One triaged finding class: ``entries``/``keys`` are fnmatch
    patterns; an event is suppressed when some entry pattern matches the
    entry name AND some key pattern matches ``"rule:key"``."""

    entries: Tuple[str, ...]
    keys: Tuple[str, ...]
    why: str


# The certifier's real findings on the current tree, triaged (ISSUE 18
# satellite 1).  tests/analysis/test_overflow.py pins this table against
# a live full run: no stale rows, no new unexplained events.
ALLOWED: Tuple[AllowRow, ...] = (
    AllowRow(
        entries=("engine-tick-scan*", "fuzz-scenario-scan-full"),
        keys=(
            "unbounded-carry:SimState.tick_index",
            "unbounded-carry:SimState.susp_deadline",
        ),
        why=(
            "int32 tick counter / tick-derived deadline: wraps at 2^31 "
            "ticks = 13.6 years of 200ms protocol periods, 4 orders past "
            "the 2^20-tick (~2.4 day) serving envelope of ROADMAP item 1 "
            "— documented headroom, not a live hazard"
        ),
    ),
    AllowRow(
        entries=("engine-tick-scan*", "fuzz-scenario-scan-full"),
        keys=(
            "unbounded-carry:SimState.inc",
            "unbounded-carry:SimState.ch_inc",
            "unbounded-carry:SimState.ch_source_inc",
            "unbounded-carry:SimState.ch_pb",
        ),
        why=(
            "incarnation stamps (and the checksum cache's stamp/budget "
            "planes) mint from the tick index: bounded by ticks+2 < 2^21 "
            "per engine._pack_key's documented invariant; the interval "
            "domain cannot see the mint-site bound through the carry"
        ),
    ),
    AllowRow(
        entries=("engine-tick-scan*", "fuzz-scenario-scan-full"),
        keys=("unbounded-carry:SimState.perm_inv",),
        why=(
            "inverse membership permutation: values are [0, N) by "
            "construction; the carry interval is polluted through "
            "stamp-dependent select chains (over-approximation), not by "
            "any arithmetic growth of the permutation itself"
        ),
    ),
    AllowRow(
        entries=("engine-tick-scan*", "fuzz-scenario-scan-full"),
        keys=(
            "unbounded-carry:SimState.ev_buf",
            "unbounded-carry:SimState.ev_drops",
            "unbounded-carry:SimState.first_heard",
        ),
        why=(
            "obs-only planes (flight-recorder ring words, drop counter, "
            "rumor wavefront stamps): tick-stamped by design and proven "
            "unable to reach the trajectory by the noninterference prong "
            "— a wrap distorts telemetry readout only"
        ),
    ),
    AllowRow(
        entries=("fuzz-scenario-scan-scalable", "engine-scalable-*"),
        keys=(
            "unbounded-carry:ScalableState.tick_index",
            "unbounded-carry:ScalableState.susp_since",
            "unbounded-carry:ScalableState.truth_inc",
            "unbounded-carry:ScalableState.r_birth",
            "unbounded-carry:ScalableState.defame_by",
        ),
        why=(
            "scalable-engine int32 tick stamps (ISSUE 18 satellite 1): "
            "suspicion start, ground-truth incarnation, rumor birth and "
            "defamer stamps all mint from tick_index and share its 2^31 "
            "wrap horizon (13.6 years at 200ms) — documented against the "
            "2^20-tick serving envelope; widening them to int64 would "
            "double the O(N)/O(U) state planes for no contract gain"
        ),
    ),
    AllowRow(
        entries=("*",),
        keys=("unbounded-carry:carry[*]",),
        why=(
            "unnamed inner-loop cursors (hash block walks, digit counts, "
            "ring binary search): bounded by data extents the interval "
            "domain cannot express (row width, log10(n) digits, log2(n) "
            "probe steps), not by per-tick growth — no cursor survives "
            "its enclosing loop"
        ),
    ),
    AllowRow(
        entries=("engine-tick-scan*", "fuzz-scenario-scan-full"),
        keys=("dtype-overflow:mul.out0",),
        why=(
            "engine._pack_key (engine.py) computes inc*4+status in int32 "
            "with the documented invariant stamps < ticks+2 (so the "
            "packed key stays < 2^22); the flagged range inherits the "
            "widened inc carry, the mint-site bound holds"
        ),
    ),
    AllowRow(
        entries=(
            "engine-tick-scan*",
            "fuzz-scenario-scan-full",
            "fused-apply-*",
            "fused-piggyback-*",
        ),
        keys=("dtype-overflow:reduce_sum.scaled.*",),
        why=(
            "int32 telemetry sums over [N,N] masks (applied_count, "
            "piggyback drops, per-tick event counts): the worst case "
            "assumes all N^2 pairs fire in one tick, real multiplicity "
            "is <= N*K; metrics-plane only, bitwise gates compare them "
            "at toy N where they are exact"
        ),
    ),
)


@functools.lru_cache(maxsize=None)
def _pat(p: str):
    """Glob where ``*`` is the ONLY metacharacter — carry keys contain
    literal ``[i]`` brackets that fnmatch would read as char classes."""
    return re.compile(
        "".join(".*" if c == "*" else re.escape(c) for c in p) + r"\Z"
    )


def _match(value: str, patterns: Sequence[str]) -> bool:
    return any(_pat(p).match(value) for p in patterns)


def allowed(
    entry: str,
    rule: str,
    key: str,
    allowlist: Sequence[AllowRow] = ALLOWED,
) -> Optional[int]:
    """Index of the first allowlist row suppressing this event, else
    None."""
    tag = f"{rule}:{key}"
    for i, row in enumerate(allowlist):
        if _match(entry, row.entries) and _match(tag, row.keys):
            return i
    return None


def _event_finding(name: str, ev: ranges.RangeEvent) -> Finding:
    where = f" [{ev.src}]" if ev.src else ""
    return Finding(
        rule=ev.rule,
        path=f"<entry:{name}>",
        line=0,
        message=(
            f"{ev.key} @ {ev.loc}{where}: {ev.detail} — fix the dtype, "
            "tighten the declared contract in ranges.ENTRY_SCALES, or "
            "triage into overflow.ALLOWED with a justification"
        ),
        prong="overflow",
    )


def _invar_names(args, closed) -> Optional[List[Optional[str]]]:
    """State-field paths for the flattened inputs, via the
    noninterference labeler; None when flatten orders disagree."""
    from ringpop_tpu.analysis import noninterference as ni

    labels = ni._flatten_labels(
        ni.label_tree(tuple(args), ni.state_registries(), "args")
    )
    if len(labels) != len(closed.jaxpr.invars):
        return None
    return [lab.path for lab in labels]


def check_entry(
    name: str,
    fn,
    args: Tuple,
    cache_as: Optional[str] = None,
    spec: Optional[ranges.ScaleSpec] = None,
    allowlist: Tuple[AllowRow, ...] = ALLOWED,
) -> Tuple[List[Finding], set]:
    """Certify one entry point; returns (findings, used allowlist row
    indices).  Ad-hoc callers (mutation tests) pass a doctored ``fn``
    with ``cache_as=None`` and usually ``allowlist=()``."""
    import jax

    findings: List[Finding] = []
    used: set = set()
    try:
        if cache_as is not None:
            from ringpop_tpu.analysis import jaxpr_audit as ja

            closed, _ = ja.trace_entry(cache_as, fn, args)
        else:
            closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:
        findings.append(
            Finding(
                rule="trace-failure",
                path=f"<entry:{name}>",
                line=0,
                message=(
                    f"entry point failed to trace: {type(e).__name__}: {e}"
                ),
                prong="overflow",
            )
        )
        return findings, used

    events = ranges.analyze_jaxpr(
        closed,
        spec or ranges.entry_scale(name),
        _invar_names(args, closed),
    )
    for ev in sorted(events, key=lambda e: (e.rule, e.key, e.loc)):
        row = allowed(name, ev.rule, ev.key, allowlist)
        if row is None:
            findings.append(_event_finding(name, ev))
        else:
            used.add(row)
    return findings, used


def check_overflow(
    entry_names: Optional[Sequence[str]] = None,
    allowlist: Tuple[AllowRow, ...] = ALLOWED,
) -> List[Finding]:
    """The prong: certify the registered entries.

    ``entry_names=None`` scans the WHOLE jaxpr registry and additionally
    reports ``stale-allowlist`` for any :data:`ALLOWED` row that
    suppressed nothing — the triage table must shrink in step with the
    code it excuses.  A subset run (--changed-only) skips staleness
    (a scoped run legitimately never reaches most rows).
    """
    from ringpop_tpu.analysis import jaxpr_audit as ja

    by_name = {ep.name: ep for ep in ja.DEFAULT_ENTRIES}
    full = entry_names is None
    names = list(by_name) if full else list(entry_names)
    findings: List[Finding] = []
    used_all: set = set()
    for name in names:
        ep = by_name.get(name)
        if ep is None:
            findings.append(
                Finding(
                    rule="trace-failure",
                    path=f"<entry:{name}>",
                    line=0,
                    message="unknown entry point",
                    prong="overflow",
                )
            )
            continue
        try:
            fn, args = ep.build()
        except Exception as e:
            findings.append(
                Finding(
                    rule="trace-failure",
                    path=f"<entry:{name}>",
                    line=0,
                    message=(
                        f"entry point setup failed: {type(e).__name__}: {e}"
                    ),
                    prong="overflow",
                )
            )
            continue
        got, used = check_entry(
            name, fn, args, cache_as=name, allowlist=allowlist
        )
        findings.extend(got)
        used_all |= used
    if full:
        for i, row in enumerate(allowlist):
            if i in used_all:
                continue
            findings.append(
                Finding(
                    rule="stale-allowlist",
                    path="ringpop_tpu/analysis/overflow.py",
                    line=0,
                    message=(
                        f"ALLOWED[{i}] ({row.keys[0]}, ...) suppressed "
                        "nothing in a full run — the finding it excuses "
                        "is gone; delete the row"
                    ),
                    prong="overflow",
                )
            )
    return findings


# --changed-only scoping: every registered entry traces code from
# these trees (entry builders span models/, ops/, parallel/, fuzz/;
# the certifier itself and its contracts are analysis/).  A change
# under none of them cannot alter any traced jaxpr, so a scoped run
# skips the prong entirely.
SOURCES: Tuple[str, ...] = (
    "analysis/",
    "models/",
    "ops/",
    "parallel/",
    "fuzz/",
)


def entries_for_changed(rel_paths: Iterable[str]) -> List[str]:
    """Entry names to re-certify for a set of changed package-relative
    paths; empty list = prong can be skipped."""
    from ringpop_tpu.analysis import jaxpr_audit as ja

    if any(r.startswith(SOURCES) for r in rel_paths):
        return [ep.name for ep in ja.DEFAULT_ENTRIES]
    return []
