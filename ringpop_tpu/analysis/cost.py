"""jaxgate prong C: static cost manifest of the compiled entry points.

The retrace prong (retrace.py) pins COMPILE COUNTS; this prong pins what
those compiles COST.  Every auditable entry point (the jaxpr prong's
registry, jaxpr_audit.DEFAULT_ENTRIES) is lowered AND compiled at its
toy shape and XLA's own static cost model is extracted:

- ``compiled.cost_analysis()`` — flops and bytes accessed,
- ``compiled.memory_analysis()`` — argument/output/temp/code sizes
  (peak device memory = args + outputs + temps).

The numbers go into a committed ``COST_BUDGET.json`` diffed in tier-1
exactly like ANALYSIS_BUDGET.json: an accidental O(N^2) blowup, a
widened dtype doubling HBM traffic, or a new temp buffer shows up as a
manifest drift and fails CI — with no chip and no wall-clock
measurement.  Regenerate with ``scripts/check_cost_budget.py --write``
after an INTENTIONAL cost change (a reviewed diff of the manifest IS
the perf review).

Backend scope: XLA's cost model is backend-specific, so the manifest
records the backend it was generated on and entries are only compared
on a matching backend (the tier-1 gate runs on CPU; a chip session can
bank a TPU manifest side by side via ``--budget``).  Pallas-lowered
entries are excluded off-TPU (they trace but do not compile there).

Tolerance: compilation is deterministic for a fixed jax/XLA build, but
the gate compares with a small relative tolerance (``DEFAULT_RTOL``) so
byte-level scheduler jitter between environments never flakes CI —
the regressions this gate exists for (dtype widenings = 2x, O(N) ->
O(N^2) = 8x at the n=8 toys... ) are far outside it.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, Iterable, List, Optional

from ringpop_tpu.analysis.findings import Finding

MANIFEST_NAME = "COST_BUDGET.json"
DEFAULT_RTOL = 0.1

# cost_analysis keys we pin (stable across jax 0.4.x CPU/TPU); the
# per-operand "bytes accessedN{}" breakdown is backend-noise and skipped
_COST_KEYS = {"flops": "flops", "bytes accessed": "bytes_accessed"}
_MEM_ATTRS = (
    "argument_size_in_bytes",
    "output_size_in_bytes",
    "temp_size_in_bytes",
)


def _entry_names_for_backend(backend: str) -> List[str]:
    from ringpop_tpu.analysis import jaxpr_audit as ja

    names = []
    for ep in ja.DEFAULT_ENTRIES:
        if backend != "tpu" and "pallas" in ep.name:
            continue  # traces everywhere, compiles only on TPU
        names.append(ep.name)
    return names


def collect_costs(
    entry_names: Optional[Iterable[str]] = None,
) -> Dict[str, dict]:
    """Compile each named entry point and extract its static costs.

    Returns ``name -> {flops, bytes_accessed, argument_size_in_bytes,
    output_size_in_bytes, temp_size_in_bytes, peak_bytes}`` — or
    ``name -> {"error": ...}`` for an entry that failed to build or
    compile (compare_to_manifest turns that into a finding;
    write_manifest refuses it)."""
    import jax

    from ringpop_tpu.analysis import jaxpr_audit as ja

    backend = jax.default_backend()
    wanted = (
        set(entry_names)
        if entry_names is not None
        else set(_entry_names_for_backend(backend))
    )
    by_name = {ep.name: ep for ep in ja.DEFAULT_ENTRIES}
    out: Dict[str, dict] = {}
    for name in sorted(wanted):
        ep = by_name.get(name)
        if ep is None:
            out[name] = {"error": "unknown entry point"}
            continue
        try:
            fn, args = ep.build()
            compiled = jax.jit(fn).lower(*args).compile()
            out[name] = _extract(compiled)
        except Exception as e:
            out[name] = {"error": "%s: %s" % (type(e).__name__, e)}
    return out


def _extract(compiled) -> dict:
    entry: Dict[str, float] = {}
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):  # jax <= 0.4.x returns [dict]
        ca = ca[0] if ca else {}
    if isinstance(ca, dict):
        for src, dst in _COST_KEYS.items():
            v = ca.get(src)
            if v is not None:
                entry[dst] = int(round(float(v)))
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        peak = 0
        for attr in _MEM_ATTRS:
            v = getattr(ma, attr, None)
            if v is not None:
                entry[attr] = int(v)
                peak += int(v)
        entry["peak_bytes"] = peak
    if not entry:
        return {"error": "backend exposed no cost/memory analysis"}
    return entry


def _drifted(actual: float, expected: float, rtol: float) -> bool:
    if actual == expected:
        return False
    scale = max(abs(expected), 1.0)
    return abs(actual - expected) > rtol * scale


def compare_to_manifest(
    actual: Dict[str, dict], manifest: dict, rtol: float = DEFAULT_RTOL
) -> List[Finding]:
    """Findings for every drift/failure between collected costs and the
    committed manifest.  Entries present in only one side are findings
    too (a new entry point must be banked; a removed one must be
    retired intentionally) — callers comparing a SUBSET pass only the
    matching manifest slice (scripts/check_cost_budget.py --entries,
    tests/analysis/test_cost_budget.py's cheap-probe gate)."""
    findings: List[Finding] = []
    expected = manifest.get("entries", {})
    for name, exp in sorted(expected.items()):
        act = actual.get(name)
        if act is None:
            findings.append(
                Finding(
                    rule="cost-budget",
                    path="<entry:%s>" % name,
                    line=0,
                    message="entry in manifest but not measured",
                    prong="cost",
                )
            )
            continue
        if "error" in act:
            findings.append(
                Finding(
                    rule="cost-failure",
                    path="<entry:%s>" % name,
                    line=0,
                    message="entry failed to compile: %s" % act["error"],
                    prong="cost",
                )
            )
            continue
        for key in sorted(set(exp) | set(act)):
            ev, av = exp.get(key), act.get(key)
            if ev is None or av is None:
                findings.append(
                    Finding(
                        rule="cost-budget",
                        path="<entry:%s>" % name,
                        line=0,
                        message=(
                            "metric %r present on only one side "
                            "(manifest %r, measured %r)" % (key, ev, av)
                        ),
                        prong="cost",
                    )
                )
            elif _drifted(av, ev, rtol):
                direction = (
                    "cost regression" if av > ev else "stale manifest"
                )
                findings.append(
                    Finding(
                        rule="cost-budget",
                        path="<entry:%s>" % name,
                        line=0,
                        message=(
                            "%s: measured %d vs manifest %d "
                            "(%+.1f%%) — %s; regenerate with "
                            "scripts/check_cost_budget.py --write if "
                            "intentional"
                            % (
                                key,
                                av,
                                ev,
                                100.0 * (av - ev) / max(ev, 1),
                                direction,
                            )
                        ),
                        prong="cost",
                    )
                )
    for name in sorted(set(actual) - set(expected)):
        act = actual[name]
        findings.append(
            Finding(
                rule="cost-failure" if "error" in act else "cost-budget",
                path="<entry:%s>" % name,
                line=0,
                message=(
                    "entry failed to compile: %s" % act["error"]
                    if "error" in act
                    else (
                        "entry has no manifest entry — regenerate with "
                        "scripts/check_cost_budget.py --write"
                    )
                ),
                prong="cost",
            )
        )
    return findings


def manifest_path(root: Optional[Path] = None) -> Path:
    if root is None:
        root = Path(__file__).resolve().parents[2]
    return root / MANIFEST_NAME


def load_manifest(path: Optional[Path] = None) -> dict:
    with open(path or manifest_path()) as f:
        return json.load(f)


def write_manifest(
    actual: Dict[str, dict], path: Optional[Path] = None
) -> Path:
    """Commit collected costs.  REFUSES entries that failed to compile —
    a manifest must never paper over a broken entry point."""
    import jax

    broken = {
        name: e["error"] for name, e in actual.items() if "error" in e
    }
    if broken:
        raise ValueError(
            "refusing to write a manifest with failed entries: %r"
            % (broken,)
        )
    p = path or manifest_path()
    doc = {
        "version": 1,
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
        "note": (
            "jaxgate static cost budget: XLA cost_analysis/"
            "memory_analysis of every auditable entry point at its toy "
            "shape (see ringpop_tpu/analysis/cost.py).  Regenerate with "
            "scripts/check_cost_budget.py --write after an INTENTIONAL "
            "cost change; the diff of this file is the perf review."
        ),
        "entries": actual,
    }
    with open(p, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return p


def check_against_manifest(
    entry_names: Optional[Iterable[str]] = None,
    path: Optional[Path] = None,
    rtol: float = DEFAULT_RTOL,
) -> List[Finding]:
    """The gate: collect + diff.  A manifest generated on a different
    backend is skipped (finding-free) — cost models do not transfer
    across backends; each banks its own manifest."""
    import jax

    try:
        manifest = load_manifest(path)
    except FileNotFoundError:
        return [
            Finding(
                rule="cost-budget",
                path=MANIFEST_NAME,
                line=0,
                message=(
                    "manifest missing — generate with "
                    "scripts/check_cost_budget.py --write"
                ),
                prong="cost",
            )
        ]
    if manifest.get("backend") != jax.default_backend():
        return []
    explicit_subset = entry_names is not None
    if entry_names is None:
        entry_names = _entry_names_for_backend(jax.default_backend())
    names = list(entry_names)
    actual = collect_costs(names)
    if explicit_subset:
        # a caller-chosen subset (tier-1 cheap probes, --entries) diffs
        # only the matching manifest slice
        sliced = dict(manifest)
        sliced["entries"] = {
            k: v
            for k, v in manifest.get("entries", {}).items()
            if k in names
        }
        return compare_to_manifest(actual, sliced, rtol=rtol)
    # full run: the WHOLE manifest is in scope, so a stale entry for a
    # removed entry point is a finding ("in manifest but not measured")
    # instead of being silently sliced away
    return compare_to_manifest(actual, manifest, rtol=rtol)
