"""jaxgate prong: static memory-feasibility ceilings (SCALE_BUDGET.json).

ISSUE 18's scale certifier consumer #2.  For every auditable entry
point (the jaxpr prong's registry), the interval certifier's footprint
model (:func:`ranges.buffer_poly`) prices the traced program as a
polynomial in N — ``{exponent: bytes_coeff}``, exponent counting
scaled dims — and a binary search finds **N\\***: the largest N at or
under the entry's declared ceiling whose total abstract footprint fits
the per-chip HBM budget.  The per-entry N\\* goes into a committed
``SCALE_BUDGET.json`` diffed by ``scripts/check_scale_budget.py``: a
refactor that adds an [N,N] temp, widens a dtype, or otherwise shrinks
the feasible scale fails STATICALLY, with no chip and no OOM run.

The polynomial deliberately overcounts (every SSA value summed, no
liveness — see buffer_poly's docstring), so N\\* is a conservative
floor on what actually fits; XLA's buffer assignment only improves on
it.  The analysis is backend-independent — unlike COST_BUDGET.json
there is no backend field and the gate always compares.

Degree is pinned too: the cheapest way to regress feasible scale is to
raise the polynomial's degree (an O(N) plan growing an O(N^2) plane),
and at entries already ceiling-bound by ``n_max`` a degree bump may
not move N\\* — so the manifest records ``degree`` and the gate
compares it exactly.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, Iterable, List, Optional

from ringpop_tpu.analysis import ranges
from ringpop_tpu.analysis.findings import Finding

MANIFEST_NAME = "SCALE_BUDGET.json"
DEFAULT_RTOL = 0.05
# per-chip HBM budget: a v4-generation 16 GiB class chip minus ~25%
# headroom for XLA scratch, the program image, and the host transfer
# staging the footprint model cannot see
HBM_BUDGET_BYTES = 12 * (1 << 30)


def entry_budget(
    name: str,
    fn,
    args,
    spec: Optional[ranges.ScaleSpec] = None,
    budget_bytes: int = HBM_BUDGET_BYTES,
    cache_as: Optional[str] = None,
) -> dict:
    """Footprint polynomial + feasible N\\* for one entry point.

    Ad-hoc callers (the oversized-buffer mutation test) pass a doctored
    ``fn`` with ``cache_as=None``."""
    import jax

    spec = spec or ranges.entry_scale(name)
    try:
        if cache_as is not None:
            from ringpop_tpu.analysis import jaxpr_audit as ja

            closed, _ = ja.trace_entry(cache_as, fn, args)
        else:
            closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:
        return {"error": "%s: %s" % (type(e).__name__, e)}
    poly = ranges.buffer_poly(closed, spec)
    n_star = ranges.feasible_n(poly, budget_bytes, spec.n_max)
    return {
        "poly_bytes": {str(e): c for e, c in sorted(poly.items())},
        "degree": max(poly) if poly else 0,
        "n_max": spec.n_max,
        "n_star": n_star,
        "ceiling_bound": n_star == spec.n_max,
    }


def collect_budgets(
    entry_names: Optional[Iterable[str]] = None,
    budget_bytes: int = HBM_BUDGET_BYTES,
) -> Dict[str, dict]:
    """``name -> entry_budget`` over the registry (or a named subset)."""
    from ringpop_tpu.analysis import jaxpr_audit as ja

    by_name = {ep.name: ep for ep in ja.DEFAULT_ENTRIES}
    wanted = set(entry_names) if entry_names is not None else set(by_name)
    out: Dict[str, dict] = {}
    for name in sorted(wanted):
        ep = by_name.get(name)
        if ep is None:
            out[name] = {"error": "unknown entry point"}
            continue
        try:
            fn, args = ep.build()
        except Exception as e:
            out[name] = {"error": "%s: %s" % (type(e).__name__, e)}
            continue
        out[name] = entry_budget(
            name, fn, args, budget_bytes=budget_bytes, cache_as=name
        )
    return out


def compare_to_manifest(
    actual: Dict[str, dict], manifest: dict, rtol: float = DEFAULT_RTOL
) -> List[Finding]:
    """Findings for every feasibility drift.

    N\\* shrinking past ``rtol`` is a scale regression; growing past it
    is a stale manifest (bank the win).  ``degree`` compares exactly.
    Entries on only one side are findings, like the cost gate."""
    findings: List[Finding] = []

    def emit(name, rule, message):
        findings.append(
            Finding(
                rule=rule,
                path="<entry:%s>" % name,
                line=0,
                message=message,
                prong="scale",
            )
        )

    expected = manifest.get("entries", {})
    for name, exp in sorted(expected.items()):
        act = actual.get(name)
        if act is None:
            emit(name, "scale-budget", "entry in manifest but not analyzed")
            continue
        if "error" in act:
            emit(
                name,
                "scale-failure",
                "entry failed to analyze: %s" % act["error"],
            )
            continue
        if act.get("degree") != exp.get("degree"):
            emit(
                name,
                "scale-budget",
                "footprint degree changed: O(N^%s) -> O(N^%s) — a new "
                "scaled plane; regenerate with scripts/"
                "check_scale_budget.py --write if intentional"
                % (exp.get("degree"), act.get("degree")),
            )
        ev, av = exp.get("n_star", 0), act.get("n_star", 0)
        if av < ev and (ev - av) > rtol * max(ev, 1):
            emit(
                name,
                "scale-budget",
                "feasible ceiling N* shrank: %d -> %d (%.1f%%) — the "
                "entry fits fewer nodes per chip than the committed "
                "budget; shrink the footprint or regenerate with "
                "scripts/check_scale_budget.py --write if intentional"
                % (ev, av, 100.0 * (ev - av) / max(ev, 1)),
            )
        elif av > ev and (av - ev) > rtol * max(ev, 1):
            emit(
                name,
                "scale-budget",
                "feasible ceiling N* grew: %d -> %d — stale manifest; "
                "bank the win with scripts/check_scale_budget.py --write"
                % (ev, av),
            )
    for name in sorted(set(actual) - set(expected)):
        act = actual[name]
        if "error" in act:
            emit(
                name,
                "scale-failure",
                "entry failed to analyze: %s" % act["error"],
            )
        else:
            emit(
                name,
                "scale-budget",
                "entry has no manifest entry — regenerate with "
                "scripts/check_scale_budget.py --write",
            )
    return findings


def manifest_path(root: Optional[Path] = None) -> Path:
    if root is None:
        root = Path(__file__).resolve().parents[2]
    return root / MANIFEST_NAME


def load_manifest(path: Optional[Path] = None) -> dict:
    with open(path or manifest_path()) as f:
        return json.load(f)


def write_manifest(
    actual: Dict[str, dict],
    path: Optional[Path] = None,
    budget_bytes: int = HBM_BUDGET_BYTES,
) -> Path:
    """Commit collected budgets.  REFUSES entries that failed to
    analyze — a broken entry point is a finding, not a budget."""
    broken = {
        name: e["error"] for name, e in actual.items() if "error" in e
    }
    if broken:
        raise ValueError(
            "refusing to write a manifest with failed entries: %r"
            % (broken,)
        )
    p = path or manifest_path()
    doc = {
        "version": 1,
        "hbm_budget_bytes": budget_bytes,
        "note": (
            "jaxgate static scale budget: abstract per-entry footprint "
            "polynomial in N and the binding-search feasible ceiling N* "
            "under the per-chip HBM budget (see ringpop_tpu/analysis/"
            "scale_budget.py).  Backend-independent.  Regenerate with "
            "scripts/check_scale_budget.py --write after an INTENTIONAL "
            "footprint change; the diff of this file is the scale "
            "review."
        ),
        "entries": actual,
    }
    with open(p, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return p


def check_against_manifest(
    entry_names: Optional[Iterable[str]] = None,
    path: Optional[Path] = None,
    rtol: float = DEFAULT_RTOL,
) -> List[Finding]:
    """The gate: analyze + diff (always — the analysis has no backend
    sensitivity).  A caller-chosen subset diffs only its manifest
    slice; a full run also catches stale manifest rows."""
    try:
        manifest = load_manifest(path)
    except FileNotFoundError:
        return [
            Finding(
                rule="scale-budget",
                path=MANIFEST_NAME,
                line=0,
                message=(
                    "manifest missing — generate with "
                    "scripts/check_scale_budget.py --write"
                ),
                prong="scale",
            )
        ]
    budget = int(manifest.get("hbm_budget_bytes", HBM_BUDGET_BYTES))
    explicit_subset = entry_names is not None
    actual = collect_budgets(entry_names, budget_bytes=budget)
    if explicit_subset:
        sliced = dict(manifest)
        sliced["entries"] = {
            k: v
            for k, v in manifest.get("entries", {}).items()
            if k in actual
        }
        return compare_to_manifest(actual, sliced, rtol=rtol)
    return compare_to_manifest(actual, manifest, rtol=rtol)
