"""jaxgate: repo-native static analysis for the device path.

Two prongs (see ISSUE 3 / README "Static analysis"):

- :mod:`ringpop_tpu.analysis.astlint` — syntax rules over ``ringpop_tpu/``
  (tick purity, dtype discipline, host-sync hygiene).
- :mod:`ringpop_tpu.analysis.jaxpr_audit` — traced-graph audit of the real
  entry points (callback-free scanned tick, uint32 hash-dataflow taint).
- :mod:`ringpop_tpu.analysis.retrace` — compile-count probes against the
  committed ``ANALYSIS_BUDGET.json`` manifest.

CLI: ``python -m ringpop_tpu.analysis`` (see ``--help``).
"""

from ringpop_tpu.analysis.findings import (  # noqa: F401
    Finding,
    render_json,
    render_text,
)
