"""jaxgate: repo-native static analysis for the device path.

The prongs are REGISTERED in :mod:`ringpop_tpu.analysis.prongs` — the
single source the CLI, ``--prong all`` and the README table derive from.
Modules (see README "Static analysis"):

- :mod:`ringpop_tpu.analysis.astlint` — syntax rules over ``ringpop_tpu/``
  (tick purity, dtype discipline, host-sync hygiene, donation aliasing).
- :mod:`ringpop_tpu.analysis.jaxpr_audit` — traced-graph audit of the real
  entry points (callback-free scanned tick, uint32 hash-dataflow taint).
- :mod:`ringpop_tpu.analysis.dataflow` — the shared jaxpr dataflow
  slicer (ONE recursive sub-jaxpr traversal; witness chains, loop
  fixpoints) under both the taint audit and the noninterference prong.
- :mod:`ringpop_tpu.analysis.noninterference` — per-entry proof that no
  obs-only input leaf reaches a trajectory output leaf (ISSUE 15).
- :mod:`ringpop_tpu.analysis.donation` — donating drivers' alias maps
  vs the committed ``DONATION_BUDGET.json`` (dropped donation = finding).
- :mod:`ringpop_tpu.analysis.retrace` / ``cost`` /
  ``kernel_coverage`` — compile-count, static-cost and kernel-twin
  budgets against their committed manifests.

CLI: ``python -m ringpop_tpu.analysis`` (see ``--help``).
"""

from ringpop_tpu.analysis.findings import (  # noqa: F401
    Finding,
    render_json,
    render_text,
)
