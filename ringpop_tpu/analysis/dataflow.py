"""jaxpr dataflow slicing: the shared recursive walker under jaxgate.

This module is the ONE place in the codebase that knows how to traverse
a ClosedJaxpr through ``pjit`` / ``scan`` / ``while`` / ``cond`` /
``shard_map`` / ``pallas_call`` sub-jaxprs (ISSUE 15).  Two consumers
ride it:

- the **hash-taint auditor** (jaxpr_audit.py) — a :class:`Visitor` whose
  per-equation hook reimplements the round-8 uint32 taint discipline
  bit-for-bit (findings text and format unchanged; the existing
  tests/analysis suite pins the refactor), and
- the **non-interference slicer** (:func:`slice_reachability`,
  noninterference.py) — label-set propagation from chosen input leaves
  to every output leaf, with witness chains naming the equations the
  flow went through.

Two traversal fidelities, selected per consumer:

``precise=False`` (audit mode) reproduces the historical walk exactly:
positional invar mapping where the inner/outer layouts line up, fully
conservative treatment of ``while`` bodies and ``pallas_call`` kernels,
and NO loop fixpoint — sub-jaxprs are walked once.

``precise=True`` (slice mode) additionally maps ``while`` bodies through
``cond_nconsts``/``body_nconsts``, maps ``cond`` branches past the
predicate, and runs ``scan``/``while`` carries to a FIXPOINT so taint
that crosses loop iterations (input -> carry -> next-iteration output)
is seen.  Control dependence is modeled: a tainted ``cond`` predicate or
``while`` condition taints every output of the equation — a value that
steers control flow steers the values it selects.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = [
    "SubJaxpr",
    "sub_jaxprs",
    "Visitor",
    "walk",
    "Witness",
    "witness_chain",
    "slice_reachability",
]


@dataclasses.dataclass(frozen=True)
class SubJaxpr:
    """One sub-jaxpr of an equation, plus how values cross its boundary.

    ``in_map[i]`` is the index into ``eqn.invars`` feeding inner invar
    ``i`` (None: no trivially positional correspondence — values cross
    the boundary conservatively).  ``out_positional`` says the inner
    outvars line up positionally with ``eqn.outvars`` (prefix-wise).
    ``carry_feedback`` lists ``(inner_out_idx, inner_in_idx)`` pairs fed
    back across loop iterations (scan/while carries; empty unless the
    walker runs in precise mode).  ``control`` marks a sub-jaxpr whose
    OUTPUT steers the equation's control flow (a while condition): its
    result taints every equation output in precise mode.
    """

    label: str
    jaxpr: object  # ClosedJaxpr or (open) Jaxpr
    in_map: Optional[List[int]]
    out_positional: bool = True
    carry_feedback: Tuple[Tuple[int, int], ...] = ()
    control: bool = False

    def open_(self) -> Tuple[object, Sequence]:
        """(open jaxpr, consts) — consts only when the sub was closed."""
        if hasattr(self.jaxpr, "jaxpr"):
            return self.jaxpr.jaxpr, self.jaxpr.consts
        return self.jaxpr, ()


def sub_jaxprs(eqn, precise: bool = False) -> List[SubJaxpr]:
    """The sub-jaxprs of ``eqn`` with boundary mappings.

    ``precise=False`` reproduces jaxpr_audit's historical traversal
    table exactly (while/cond-mismatch/pallas conservative, no
    feedback); ``precise=True`` adds the while/scan/cond structure the
    slicer needs.
    """
    import jax

    prim = eqn.primitive.name
    params = eqn.params
    out: List[SubJaxpr] = []

    def positional(j) -> Optional[List[int]]:
        n_inner = len(j.jaxpr.invars if hasattr(j, "jaxpr") else j.invars)
        if n_inner == len(eqn.invars):
            return list(range(len(eqn.invars)))
        return None

    if prim in ("pjit", "closed_call", "core_call", "xla_call", "remat"):
        j = params.get("jaxpr") or params.get("call_jaxpr")
        if j is not None:
            out.append(SubJaxpr(prim, j, positional(j)))
    elif prim == "scan":
        j = params["jaxpr"]
        feedback: Tuple[Tuple[int, int], ...] = ()
        if precise:
            nc = params.get("num_consts", 0)
            feedback = tuple(
                (i, nc + i) for i in range(params.get("num_carry", 0))
            )
        out.append(
            SubJaxpr(prim, j, positional(j), carry_feedback=feedback)
        )
    elif prim == "while":
        cond_j = params["cond_jaxpr"]
        body_j = params["body_jaxpr"]
        if not precise:
            out.append(SubJaxpr("while_cond", cond_j, None))
            out.append(SubJaxpr("while_body", body_j, None))
        else:
            cn = params.get("cond_nconsts", 0)
            bn = params.get("body_nconsts", 0)
            n_carry = len(eqn.invars) - cn - bn
            cond_map = list(range(cn)) + [
                cn + bn + i for i in range(n_carry)
            ]
            body_map = [cn + i for i in range(bn)] + [
                cn + bn + i for i in range(n_carry)
            ]
            out.append(
                SubJaxpr(
                    "while_cond",
                    cond_j,
                    cond_map,
                    out_positional=False,
                    control=True,
                )
            )
            out.append(
                SubJaxpr(
                    "while_body",
                    body_j,
                    body_map,
                    carry_feedback=tuple(
                        (i, bn + i) for i in range(n_carry)
                    ),
                )
            )
    elif prim == "cond":
        for k, branch in enumerate(params["branches"]):
            n_inner = len(branch.jaxpr.invars)
            mapping = (
                list(range(1, len(eqn.invars)))
                if n_inner == len(eqn.invars) - 1
                else None
            )
            out.append(SubJaxpr(f"cond_branch{k}", branch, mapping))
    elif prim == "shard_map":
        # the boundary is positional 1:1 (in_names/out_names reshard,
        # they don't reorder), so slice mode keeps per-position
        # separation — without this the round-17 telemetry planes
        # entering the exchange plane would conservatively taint the
        # heard tile coming out.  Audit mode keeps its historical
        # conservative fallback (pinned findings text).
        j = params.get("jaxpr")
        if j is not None:
            if precise:
                out.append(SubJaxpr(prim, j, positional(j)))
            else:
                out.append(
                    SubJaxpr(f"{prim}.jaxpr", j, None, out_positional=False)
                )
    elif prim in ("custom_jvp_call", "custom_vjp_call", "custom_jvp_call_jaxpr"):
        j = params.get("call_jaxpr") or params.get("fun_jaxpr")
        if j is not None:
            out.append(SubJaxpr(prim, j, positional(j)))
    else:
        # generic fallback (pallas_call kernels, checkpoint, ...): find
        # any jaxpr-valued param and walk it with constant-only seeding
        for key, val in params.items():
            if isinstance(val, (jax.core.ClosedJaxpr, jax.core.Jaxpr)):
                out.append(
                    SubJaxpr(f"{prim}.{key}", val, None, out_positional=False)
                )
            elif isinstance(val, (tuple, list)):
                for k, item in enumerate(val):
                    if isinstance(
                        item, (jax.core.ClosedJaxpr, jax.core.Jaxpr)
                    ):
                        out.append(
                            SubJaxpr(
                                f"{prim}.{key}[{k}]",
                                item,
                                None,
                                out_positional=False,
                            )
                        )
    return out


class Visitor:
    """Per-equation hooks driven by :func:`walk`.

    A visitor defines the abstract value propagated through the jaxpr
    (``bottom`` + ``join`` form the lattice), seeds values at constvars
    and literals, and computes each equation's output values — emitting
    findings as a side effect if it wants.  ``measure`` maps a value to
    something hashable so the walker's loop fixpoints can detect
    convergence without comparing witnesses.
    """

    bottom = None
    precise = False  # traversal fidelity (see module docstring)
    fixpoint = False  # iterate scan/while carries to a fixpoint

    def join(self, a, b):
        raise NotImplementedError

    def measure(self, val):
        return val

    def seed_constvar(self, var, const):
        return self.bottom

    def literal(self, lit):
        return self.bottom

    def enter_eqn(self, eqn, stack: Tuple[str, ...], in_vals: List) -> None:
        """Called once per equation before sub-jaxpr recursion."""

    def eqn_out(
        self,
        eqn,
        stack: Tuple[str, ...],
        in_vals: List,
        subs: List[SubJaxpr],
        sub_out_vals: List[List],
    ) -> List:
        raise NotImplementedError


def walk(
    jaxpr,
    consts: Sequence,
    stack: Tuple[str, ...],
    in_vals: Sequence,
    visitor: Visitor,
) -> List:
    """Propagate ``visitor`` values through one (open) jaxpr.

    Returns the values at ``jaxpr.outvars``.  The recursion through
    sub-jaxprs and the optional carry fixpoint live here — consumers
    only see per-equation hooks.
    """
    import jax

    env: Dict[object, object] = {}
    for var, val in zip(jaxpr.invars, in_vals):
        env[var] = val
    for var, const in zip(jaxpr.constvars, consts):
        env[var] = visitor.seed_constvar(var, const)

    def val_of(v):
        if isinstance(v, jax.core.Literal):
            return visitor.literal(v)
        return env.get(v, visitor.bottom)

    def walk_sub(sub: SubJaxpr, cur_in: List) -> List:
        inner, inner_consts = sub.open_()
        n_inner = len(inner.invars)
        if sub.in_map is not None:
            inner_in = [
                cur_in[sub.in_map[i]]
                if i < len(sub.in_map)
                else visitor.bottom
                for i in range(n_inner)
            ]
        else:
            inner_in = [visitor.bottom] * n_inner
        while True:
            ov = walk(
                inner,
                inner_consts,
                stack + (sub.label,),
                inner_in,
                visitor,
            )
            if not (visitor.fixpoint and sub.carry_feedback):
                return ov
            changed = False
            for oi, ii in sub.carry_feedback:
                if oi >= len(ov) or ii >= n_inner:
                    continue
                joined = visitor.join(inner_in[ii], ov[oi])
                if visitor.measure(joined) != visitor.measure(
                    inner_in[ii]
                ):
                    inner_in[ii] = joined
                    changed = True
            if not changed:
                # soundness: write the converged carry values back into
                # the equation's input view, so sibling subs walked
                # AFTER this one (a while condition) see taint that only
                # enters the carry on a later iteration
                if sub.in_map is not None:
                    for _oi, ii in sub.carry_feedback:
                        if ii < len(sub.in_map):
                            cur_in[sub.in_map[ii]] = inner_in[ii]
                return ov

    for eqn in jaxpr.eqns:
        cur_in = [val_of(v) for v in eqn.invars]
        visitor.enter_eqn(eqn, stack, cur_in)
        subs = sub_jaxprs(eqn, precise=visitor.precise)
        sub_out_vals: List[Optional[List]] = [None] * len(subs)
        if visitor.fixpoint:
            # loop bodies first (their fixpoint updates cur_in's carry
            # view), then control/other subs against the updated carries
            order = sorted(
                range(len(subs)), key=lambda i: not subs[i].carry_feedback
            )
        else:
            order = list(range(len(subs)))
        for i in order:
            sub_out_vals[i] = walk_sub(subs[i], cur_in)
        outs = visitor.eqn_out(eqn, stack, cur_in, subs, sub_out_vals)
        for var, val in zip(eqn.outvars, outs):
            if isinstance(var, jax.core.DropVar):
                continue
            env[var] = val
    return [val_of(v) for v in jaxpr.outvars]


# ---------------------------------------------------------------------------
# the non-interference slicer: label-set reachability with witness chains


@dataclasses.dataclass(frozen=True)
class Witness:
    """One hop of an input->output flow: the equation that carried it.

    Witnesses form a shared-structure linked list back toward the seed
    (``prev``); :func:`witness_chain` renders one as the human-readable
    eqn chain a finding prints.  Join keeps the FIRST witness per label,
    so chains stay stable (and memory bounded) across loop fixpoints.
    """

    prim: str
    loc: str  # "/".join(stack) at the carrying equation
    prev: Optional["Witness"] = None


def witness_chain(w: Optional[Witness], limit: int = 8) -> str:
    """Render a witness as ``seed-side -> ... -> output-side`` text."""
    hops: List[str] = []
    while w is not None:
        loc = w.loc or "<top>"
        hops.append(f"{w.prim}@{loc}")
        w = w.prev
    hops.reverse()
    if len(hops) > limit:
        head = limit // 2
        tail = limit - head
        omitted = len(hops) - limit
        hops = hops[:head] + [f"... ({omitted} eqns) ..."] + hops[-tail:]
    return " -> ".join(hops) if hops else "<direct>"


class _SliceVisitor(Visitor):
    """val = {label: Witness}.  Conservative per-equation propagation:
    with no sub-jaxprs every output sees every input (primitive
    semantics are not modeled — a scatter's indices legitimately steer
    its output); positionally mapped sub-jaxprs keep their per-position
    separation, which is what makes the slice precise where it matters
    (the scanned state carry)."""

    bottom: Dict = {}
    precise = True
    fixpoint = True

    def join(self, a, b):
        if not b:
            return a
        if not a:
            return b
        merged = dict(a)
        for k, v in b.items():
            merged.setdefault(k, v)
        return merged

    def measure(self, val):
        return frozenset(val)

    def eqn_out(self, eqn, stack, in_vals, subs, sub_out_vals):
        n_out = len(eqn.outvars)
        prim = eqn.primitive.name
        loc = "/".join(stack)

        def extend(val):
            if not val:
                return self.bottom
            return {
                k: Witness(prim, loc, prev=w) for k, w in val.items()
            }

        if not subs:
            flowed = self.bottom
            for v in in_vals:
                flowed = self.join(flowed, v)
            out = extend(flowed)
            return [out] * n_out

        outs: List[Dict] = [self.bottom] * n_out
        spill = self.bottom  # joins into every output
        mapped_in: set = set()
        for sub, ov in zip(subs, sub_out_vals):
            if sub.in_map is not None:
                mapped_in.update(sub.in_map)
            if sub.control or not sub.out_positional:
                for v in ov:
                    spill = self.join(spill, v)
            else:
                for i in range(min(n_out, len(ov))):
                    outs[i] = self.join(outs[i], ov[i])
            # zero-iteration identity: a while that never runs (and a
            # length-0 scan) returns its INITIAL carry, so carry inputs
            # reach the matching outputs even when the body overwrites
            # the slot — dropping this would let an obs-tainted carry
            # slip out unlabeled
            if sub.carry_feedback and sub.in_map is not None:
                for oi, ii in sub.carry_feedback:
                    if oi < n_out and ii < len(sub.in_map):
                        outs[oi] = self.join(
                            outs[oi], extend(in_vals[sub.in_map[ii]])
                        )
        # equation inputs no sub-jaxpr consumed positionally (a cond
        # predicate, pallas operands) flow conservatively to every out
        for i, v in enumerate(in_vals):
            if i not in mapped_in:
                spill = self.join(spill, v)
        if spill:
            spill = extend(spill)
            outs = [self.join(o, spill) for o in outs]
        return outs


def slice_reachability(
    closed, seed_labels: Sequence[Optional[str]]
) -> List[Dict[str, Witness]]:
    """Input->output reachability over a ClosedJaxpr.

    ``seed_labels[i]`` labels flattened input leaf ``i`` (None: not
    tracked).  Returns, per flattened output leaf, ``{label: Witness}``
    for every seeded input that can reach it — transitively, through
    every sub-jaxpr, with loop carries run to a fixpoint.
    """
    jaxpr = closed.jaxpr
    if len(seed_labels) != len(jaxpr.invars):
        raise ValueError(
            f"seed_labels has {len(seed_labels)} entries for "
            f"{len(jaxpr.invars)} jaxpr inputs"
        )
    visitor = _SliceVisitor()
    in_vals = [
        {lab: Witness("<input>", "")} if lab is not None else {}
        for lab in seed_labels
    ]
    return walk(jaxpr, closed.consts, (), in_vals, visitor)
