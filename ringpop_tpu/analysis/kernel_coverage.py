"""Kernel-coverage prong: every Pallas kernel under ops/ must have a
registered bit-exact twin and a gate-equivalence test.

The toolkit pattern (ops/toolkit.py) requires every ``pallas_call``
under ``ringpop_tpu/ops/`` to ship with a pure-XLA twin and a test
pinning their bitwise equality — the rounds-7/10/14 kernels all did,
by convention.  This prong makes the convention MACHINE-CHECKED, in the
required-coverage style of the jaxpr registry gate: the AST is walked
for ``pallas_call`` call sites, and every module containing one must
have a ``toolkit.TWIN_REGISTRY`` row whose kernel entry, twin entry and
gate test all exist (and the test must mention the kernel entry by
name, so a renamed entry cannot silently orphan its gate).  A mutation
test proves the rule fires on an unregistered kernel
(tests/analysis/test_kernel_coverage.py).

Findings (prong "kernels"):

- ``unregistered-kernel`` — a module under ops/ calls ``pallas_call``
  but has no TWIN_REGISTRY row;
- ``missing-kernel-entry`` / ``missing-twin-entry`` — a registry row
  names a function that does not exist in its module;
- ``missing-gate-test`` — the registered test file does not exist or
  never mentions the kernel entry;
- ``stale-registry-row`` — a registry row's module has no
  ``pallas_call`` at all (the kernel was removed; drop the row).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, List, Optional, Sequence

from ringpop_tpu.analysis.findings import Finding

PRONG = "kernels"

# the toolkit module ITSELF holds the one shared gridless pallas_call
# (stream_row_tiles, the scaffold every row-streaming kernel lowers
# through) — it is infrastructure, not a kernel; the kernels built on
# it are detected via their stream_row_tiles call sites instead
EXEMPT_MODULES = frozenset({"toolkit"})


def _module_paths(ops_root: Path) -> List[Path]:
    return sorted(p for p in ops_root.glob("*.py") if p.name != "__init__.py")


def _pallas_call_lines(tree: ast.AST) -> List[int]:
    """Line numbers of Pallas kernel call sites: direct ``pallas_call``
    (attribute or bare name) and the toolkit scaffold
    (``stream_row_tiles`` — the shared gridless pallas_call every
    row-streaming kernel lowers through)."""
    lines = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        name = None
        if isinstance(fn, ast.Attribute):
            name = fn.attr
        elif isinstance(fn, ast.Name):
            name = fn.id
        if name in ("pallas_call", "stream_row_tiles"):
            lines.append(node.lineno)
    return lines


def _toplevel_defs(tree: ast.AST) -> set:
    return {
        node.name
        for node in ast.iter_child_nodes(tree)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
    }


def check_kernel_coverage(
    ops_root: Optional[Path] = None,
    registry: Optional[Sequence] = None,
    repo_root: Optional[Path] = None,
) -> List[Finding]:
    """Run the coverage rule.  ``ops_root``/``registry``/``repo_root``
    default to the live tree and ``toolkit.TWIN_REGISTRY`` — the
    overrides exist so the mutation tests can point the rule at a
    doctored tree and prove it fires."""
    from ringpop_tpu.ops import toolkit

    if ops_root is None:
        ops_root = Path(toolkit.__file__).resolve().parent
    if repo_root is None:
        repo_root = ops_root.parents[1]
    if registry is None:
        registry = toolkit.TWIN_REGISTRY

    findings: List[Finding] = []
    trees = {}
    kernel_modules = {}
    for path in _module_paths(ops_root):
        try:
            tree = ast.parse(path.read_text())
        except SyntaxError as e:
            findings.append(
                Finding(
                    rule="unregistered-kernel",
                    path=str(path),
                    line=e.lineno or 0,
                    message="unparseable ops module: %s" % e,
                    prong=PRONG,
                )
            )
            continue
        trees[path.stem] = tree
        lines = _pallas_call_lines(tree)
        if lines and path.stem not in EXEMPT_MODULES:
            kernel_modules[path.stem] = (path, lines)

    by_module: dict = {}
    for row in registry:
        by_module.setdefault(row.module, []).append(row)

    for mod, (path, lines) in sorted(kernel_modules.items()):
        if mod not in by_module:
            findings.append(
                Finding(
                    rule="unregistered-kernel",
                    path=str(path),
                    line=lines[0],
                    message=(
                        "ops/%s.py holds a pallas_call but has no "
                        "toolkit.TWIN_REGISTRY row — register its "
                        "bit-exact twin and gate-equivalence test"
                        % mod
                    ),
                    prong=PRONG,
                )
            )

    for row in registry:
        if row.module not in trees:
            findings.append(
                Finding(
                    rule="stale-registry-row",
                    path="<registry:%s>" % row.module,
                    line=0,
                    message=(
                        "TWIN_REGISTRY names ops module %r which does "
                        "not exist" % row.module
                    ),
                    prong=PRONG,
                )
            )
            continue
        if row.module not in kernel_modules:
            findings.append(
                Finding(
                    rule="stale-registry-row",
                    path="<registry:%s>" % row.module,
                    line=0,
                    message=(
                        "TWIN_REGISTRY row %s.%s registered but "
                        "ops/%s.py holds no pallas_call — drop the row"
                        % (row.module, row.kernel_entry, row.module)
                    ),
                    prong=PRONG,
                )
            )
        if row.kernel_entry not in _toplevel_defs(trees[row.module]):
            findings.append(
                Finding(
                    rule="missing-kernel-entry",
                    path="<registry:%s>" % row.module,
                    line=0,
                    message=(
                        "registered kernel entry %s.%s does not exist"
                        % (row.module, row.kernel_entry)
                    ),
                    prong=PRONG,
                )
            )
        twin_mod = row.twin_module or row.module
        if twin_mod not in trees or row.twin_entry not in _toplevel_defs(
            trees[twin_mod]
        ):
            findings.append(
                Finding(
                    rule="missing-twin-entry",
                    path="<registry:%s>" % row.module,
                    line=0,
                    message=(
                        "registered twin %s.%s does not exist"
                        % (twin_mod, row.twin_entry)
                    ),
                    prong=PRONG,
                )
            )
        test_path = repo_root / row.gate_test
        if not test_path.is_file():
            findings.append(
                Finding(
                    rule="missing-gate-test",
                    path=row.gate_test,
                    line=0,
                    message=(
                        "gate-equivalence test %s for %s.%s does not "
                        "exist" % (row.gate_test, row.module,
                                   row.kernel_entry)
                    ),
                    prong=PRONG,
                )
            )
        elif row.kernel_entry not in test_path.read_text():
            findings.append(
                Finding(
                    rule="missing-gate-test",
                    path=row.gate_test,
                    line=0,
                    message=(
                        "gate test %s never mentions kernel entry %r — "
                        "a rename orphaned the gate"
                        % (row.gate_test, row.kernel_entry)
                    ),
                    prong=PRONG,
                )
            )
    return findings
