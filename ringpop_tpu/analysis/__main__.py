"""jaxgate CLI: ``python -m ringpop_tpu.analysis``.

Runs the registered prongs (see :mod:`ringpop_tpu.analysis.prongs` —
the one registry CLI help, ``--prong all`` and the README table derive
from) over the repo and exits non-zero on any unsuppressed finding.
The default set is the cheap one (nothing that compiles entry points);
``retrace``/``cost``/``donation`` compile real entry points and are
opt-in — CI runs them via their ``scripts/check_*_budget.py`` twins.

Examples::

    python -m ringpop_tpu.analysis                       # default prongs
    python -m ringpop_tpu.analysis --format json         # + per-prong wall time
    python -m ringpop_tpu.analysis --prong ast ringpop_tpu/ops/native.py
    python -m ringpop_tpu.analysis --prong noninterference,donation
    python -m ringpop_tpu.analysis --changed-only        # pre-commit speed
    python -m ringpop_tpu.analysis --list-rules
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path
from typing import List, Optional

from ringpop_tpu.analysis import astlint, findings as fmod
from ringpop_tpu.analysis.prongs import ALL_PRONGS, DEFAULT_PRONGS, PRONGS

PKG_ROOT = Path(__file__).resolve().parents[1]  # .../ringpop_tpu
REPO_ROOT = PKG_ROOT.parent

# jaxpr-audited modules: a scoped run skips the (slower) trace prong
# unless one of these is in scope.  Derived from the jit-root registry so
# a newly registered entry module is automatically covered; gating.py is
# traced through both engines' phase wrappers without being a root itself.
_JAXPR_SOURCES = tuple(astlint.TRACED_ENTRIES) + ("models/sim/gating.py",)


def _changed_files() -> List[Path]:
    out: set = set()
    for cmd in (
        ["git", "diff", "--name-only"],
        ["git", "diff", "--name-only", "--cached"],
        # brand-new files the developer has not staged yet
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                cmd,
                cwd=REPO_ROOT,
                capture_output=True,
                text=True,
                check=True,
            )
        except (subprocess.CalledProcessError, FileNotFoundError):
            continue
        out.update(line.strip() for line in proc.stdout.splitlines())
    return [
        REPO_ROOT / f
        for f in sorted(out)
        if f.endswith(".py")
        and f.startswith("ringpop_tpu/")
        and (REPO_ROOT / f).exists()
    ]


def _pkg_rel(files: List[Path]) -> List[str]:
    """Package-relative posix paths ('models/sim/engine.py') for the
    touched-module -> affected-entry-point mappings."""
    out = []
    for f in files:
        r = f.resolve()
        if r.is_relative_to(PKG_ROOT):
            out.append(r.relative_to(PKG_ROOT).as_posix())
    return out


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ringpop_tpu.analysis",
        description="jaxgate: machine-checked static analysis for ringpop-tpu",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to lint (default: the ringpop_tpu package)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text"
    )
    parser.add_argument(
        "--prong",
        default=",".join(DEFAULT_PRONGS),
        help=(
            "comma list of prongs to run: %s (or 'all'; default %s — "
            "%s compile real entry points and are opt-in; CI runs them "
            "via their scripts/check_*_budget.py twins)"
            % (
                ", ".join(ALL_PRONGS),
                ",".join(DEFAULT_PRONGS),
                "/".join(p for p in ALL_PRONGS if not PRONGS[p].default),
            )
        ),
    )
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help="lint only files named by git diff --name-only (+ --cached)",
    )
    parser.add_argument(
        "--budget",
        default=None,
        help="retrace manifest path (default: ANALYSIS_BUDGET.json at repo root)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in astlint.ALL_RULES:
            print(f"{rule.name:26s} [{rule.scope}]")
            print(f"    {rule.summary}")
        print()
        for spec in PRONGS.values():
            default = "default" if spec.default else "opt-in"
            print(f"{spec.name} prong ({default}): {', '.join(spec.rules)}")
            print(f"    {spec.summary}")
            print(f"    CI: {spec.ci}")
        print(
            "\nsuppress per line with  # jaxgate: ignore[rule-a,rule-b]  "
            "(bare 'ignore' silences all);\nmark a trace-time host helper "
            "with  # jaxgate: host  on its def line"
        )
        return 0

    prongs = (
        set(ALL_PRONGS)
        if args.prong.strip() == "all"
        else {p.strip() for p in args.prong.split(",") if p.strip()}
    )
    unknown = prongs - set(ALL_PRONGS)
    if unknown:
        parser.error(f"unknown prong(s): {sorted(unknown)}")

    all_findings: List[fmod.Finding] = []
    prong_seconds: dict = {}

    from ringpop_tpu.obs.perf import stopwatch

    files: Optional[List[Path]] = None
    if args.changed_only:
        files = _changed_files()
    if args.paths:
        explicit: List[Path] = []
        for p in args.paths:
            path = Path(p)
            if not path.exists() and not path.is_absolute():
                # repo-relative paths must work from any cwd (pre-commit
                # hooks run wherever they please)
                anchored = REPO_ROOT / p
                if anchored.exists():
                    path = anchored
            if path.is_dir():
                explicit.extend(
                    sorted(
                        f
                        for f in path.rglob("*.py")
                        if "__pycache__" not in f.parts
                    )
                )
            else:
                explicit.append(path)
        if files is None:
            files = explicit
        else:
            explicit_set = {e.resolve() for e in explicit}
            files = [f for f in files if f.resolve() in explicit_set]

    scoped_rel = _pkg_rel(files) if files is not None else None

    if "ast" in prongs:
        with stopwatch(prong_seconds, "ast"):
            all_findings.extend(astlint.lint_paths(PKG_ROOT, files=files))

    if "jaxpr" in prongs:
        run_jaxpr = True
        if scoped_rel is not None:
            # a scoped run (--changed-only or explicit paths) only pays
            # for the multi-second entry-point traces when a file the
            # jaxpr prong actually covers is in scope
            run_jaxpr = any(src in scoped_rel for src in _JAXPR_SOURCES)
        if run_jaxpr:
            from ringpop_tpu.analysis import jaxpr_audit

            with stopwatch(prong_seconds, "jaxpr"):
                all_findings.extend(jaxpr_audit.audit_entries())

    if "kernels" in prongs:
        from ringpop_tpu.analysis import kernel_coverage

        with stopwatch(prong_seconds, "kernels"):
            all_findings.extend(kernel_coverage.check_kernel_coverage())

    if "noninterference" in prongs:
        from ringpop_tpu.analysis import noninterference

        entry_names = None
        if scoped_rel is not None:
            # touched-module -> affected-entry-point mapping: a scoped
            # run re-proves only the entries a changed module can feed
            entry_names = noninterference.entries_for_changed(scoped_rel)
        if entry_names is None or entry_names:
            with stopwatch(prong_seconds, "noninterference"):
                all_findings.extend(
                    noninterference.check_noninterference(entry_names)
                )

    if "overflow" in prongs:
        from ringpop_tpu.analysis import overflow

        entry_names = None
        if scoped_rel is not None:
            # same touched-module gate as noninterference: a scoped run
            # only pays for the interval sweep when certifier-relevant
            # sources changed (a full sweep — allowlist rows are keyed
            # by entry patterns, so partial sweeps would false-stale)
            entry_names = overflow.entries_for_changed(scoped_rel)
        if entry_names is None or entry_names:
            with stopwatch(prong_seconds, "overflow"):
                all_findings.extend(overflow.check_overflow(entry_names))

    if "scale" in prongs:
        from ringpop_tpu.analysis import overflow, scale_budget

        entry_names = None
        if scoped_rel is not None:
            entry_names = overflow.entries_for_changed(scoped_rel)
        if entry_names is None or entry_names:
            with stopwatch(prong_seconds, "scale"):
                all_findings.extend(
                    scale_budget.check_against_manifest(entry_names)
                )

    if "donation" in prongs:
        from ringpop_tpu.analysis import donation

        run_donation = True
        if scoped_rel is not None:
            run_donation = any(
                r.startswith(donation.SOURCES) for r in scoped_rel
            )
        if run_donation:
            with stopwatch(prong_seconds, "donation"):
                all_findings.extend(donation.check_against_manifest())

    if "retrace" in prongs:
        from ringpop_tpu.analysis import retrace

        path = Path(args.budget) if args.budget else None
        with stopwatch(prong_seconds, "retrace"):
            all_findings.extend(retrace.check_against_manifest(path=path))

    if "cost" in prongs:
        from ringpop_tpu.analysis import cost

        # --budget names the RETRACE manifest; the cost prong always
        # reads the repo-root COST_BUDGET.json here (the script exposes
        # its own --budget for alternate paths)
        with stopwatch(prong_seconds, "cost"):
            all_findings.extend(cost.check_against_manifest())

    if args.format == "json":
        # per-prong wall time rides in the JSON output so the tier-1
        # analysis budget stays observable (ISSUE 15 satellite)
        print(
            fmod.render_json(
                all_findings,
                meta={
                    "prong_seconds": {
                        k: round(v, 3) for k, v in prong_seconds.items()
                    }
                },
            )
        )
    else:
        print(fmod.render_text(all_findings))
    return 1 if all_findings else 0


if __name__ == "__main__":
    sys.exit(main())
