"""jaxgate CLI: ``python -m ringpop_tpu.analysis``.

Runs the AST lint (prong B) and the jaxpr auditor (prong A) over the
repo and exits non-zero on any unsuppressed finding.  The retrace-budget
prong compiles real entry points and is opt-in (``--prong all`` or
``--prong retrace``); CI runs it via ``scripts/check_retrace_budget.py``.

Examples::

    python -m ringpop_tpu.analysis                       # lint + jaxpr audit
    python -m ringpop_tpu.analysis --format json
    python -m ringpop_tpu.analysis --prong ast ringpop_tpu/ops/native.py
    python -m ringpop_tpu.analysis --changed-only        # pre-commit speed
    python -m ringpop_tpu.analysis --list-rules
"""

from __future__ import annotations

import argparse
import subprocess
import sys
from pathlib import Path
from typing import List, Optional

from ringpop_tpu.analysis import astlint, findings as fmod

PKG_ROOT = Path(__file__).resolve().parents[1]  # .../ringpop_tpu
REPO_ROOT = PKG_ROOT.parent

# jaxpr-audited modules: a scoped run skips the (slower) trace prong
# unless one of these is in scope.  Derived from the jit-root registry so
# a newly registered entry module is automatically covered; gating.py is
# traced through both engines' phase wrappers without being a root itself.
_JAXPR_SOURCES = tuple(astlint.TRACED_ENTRIES) + ("models/sim/gating.py",)


def _changed_files() -> List[Path]:
    out: set = set()
    for cmd in (
        ["git", "diff", "--name-only"],
        ["git", "diff", "--name-only", "--cached"],
        # brand-new files the developer has not staged yet
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                cmd,
                cwd=REPO_ROOT,
                capture_output=True,
                text=True,
                check=True,
            )
        except (subprocess.CalledProcessError, FileNotFoundError):
            continue
        out.update(line.strip() for line in proc.stdout.splitlines())
    return [
        REPO_ROOT / f
        for f in sorted(out)
        if f.endswith(".py")
        and f.startswith("ringpop_tpu/")
        and (REPO_ROOT / f).exists()
    ]


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m ringpop_tpu.analysis",
        description="jaxgate: jaxpr auditor + AST lint for ringpop-tpu",
    )
    parser.add_argument(
        "paths",
        nargs="*",
        help="files/dirs to lint (default: the ringpop_tpu package)",
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text"
    )
    parser.add_argument(
        "--prong",
        default="ast,jaxpr,kernels",
        help=(
            "comma list of prongs to run: ast, jaxpr, kernels, retrace, "
            "cost (or 'all'; default ast,jaxpr,kernels — retrace/cost "
            "compile real entry points and are opt-in; CI runs them via "
            "scripts/check_retrace_budget.py / check_cost_budget.py)"
        ),
    )
    parser.add_argument(
        "--changed-only",
        action="store_true",
        help="lint only files named by git diff --name-only (+ --cached)",
    )
    parser.add_argument(
        "--budget",
        default=None,
        help="retrace manifest path (default: ANALYSIS_BUDGET.json at repo root)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule table"
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule in astlint.ALL_RULES:
            print(f"{rule.name:20s} [{rule.scope}]")
            print(f"    {rule.summary}")
        print(
            "\njaxpr prong: callback-primitive, wide-dtype-on-hash-path, "
            "trace-failure\nkernels prong: unregistered-kernel, "
            "missing-kernel-entry, missing-twin-entry, missing-gate-test, "
            "stale-registry-row\nretrace prong: retrace-budget"
            "\ncost prong: cost-budget, cost-failure"
        )
        print(
            "\nsuppress per line with  # jaxgate: ignore[rule-a,rule-b]  "
            "(bare 'ignore' silences all);\nmark a trace-time host helper "
            "with  # jaxgate: host  on its def line"
        )
        return 0

    prongs = (
        {"ast", "jaxpr", "kernels", "retrace", "cost"}
        if args.prong.strip() == "all"
        else {p.strip() for p in args.prong.split(",") if p.strip()}
    )
    unknown = prongs - {"ast", "jaxpr", "kernels", "retrace", "cost"}
    if unknown:
        parser.error(f"unknown prong(s): {sorted(unknown)}")

    all_findings: List[fmod.Finding] = []

    files: Optional[List[Path]] = None
    if args.changed_only:
        files = _changed_files()
    if args.paths:
        explicit: List[Path] = []
        for p in args.paths:
            path = Path(p)
            if not path.exists() and not path.is_absolute():
                # repo-relative paths must work from any cwd (pre-commit
                # hooks run wherever they please)
                anchored = REPO_ROOT / p
                if anchored.exists():
                    path = anchored
            if path.is_dir():
                explicit.extend(
                    sorted(
                        f
                        for f in path.rglob("*.py")
                        if "__pycache__" not in f.parts
                    )
                )
            else:
                explicit.append(path)
        if files is None:
            files = explicit
        else:
            explicit_set = {e.resolve() for e in explicit}
            files = [f for f in files if f.resolve() in explicit_set]

    if "ast" in prongs:
        all_findings.extend(astlint.lint_paths(PKG_ROOT, files=files))

    if "jaxpr" in prongs:
        run_jaxpr = True
        if files is not None:
            # a scoped run (--changed-only or explicit paths) only pays
            # for the multi-second entry-point traces when a file the
            # jaxpr prong actually covers is in scope
            scoped_rel = {
                f.resolve().relative_to(PKG_ROOT).as_posix()
                for f in files
                if f.resolve().is_relative_to(PKG_ROOT)
            }
            run_jaxpr = any(
                src in scoped_rel for src in _JAXPR_SOURCES
            )
        if run_jaxpr:
            from ringpop_tpu.analysis import jaxpr_audit

            all_findings.extend(jaxpr_audit.audit_entries())

    if "kernels" in prongs:
        from ringpop_tpu.analysis import kernel_coverage

        all_findings.extend(kernel_coverage.check_kernel_coverage())

    if "retrace" in prongs:
        from ringpop_tpu.analysis import retrace

        path = Path(args.budget) if args.budget else None
        all_findings.extend(retrace.check_against_manifest(path=path))

    if "cost" in prongs:
        from ringpop_tpu.analysis import cost

        # --budget names the RETRACE manifest; the cost prong always
        # reads the repo-root COST_BUDGET.json here (the script exposes
        # its own --budget for alternate paths)
        all_findings.extend(cost.check_against_manifest())

    if args.format == "json":
        print(fmod.render_json(all_findings))
    else:
        print(fmod.render_text(all_findings))
    return 1 if all_findings else 0


if __name__ == "__main__":
    sys.exit(main())
