"""The jaxgate prong registry — the ONE place a prong is declared.

CLI help, ``--prong all`` expansion, the default prong set,
``--list-rules`` output and the README "Static analysis" prong table all
derive from :data:`PRONGS` (tests/analysis/test_prong_registry.py pins
the README table against it), so they cannot drift from each other.

Adding a prong = adding a :class:`ProngSpec` here plus its runner arm in
``__main__`` — a registered prong with no runner arm is caught by
``tests/analysis/test_prong_registry.py`` (a source-level
dispatch-coverage check), so the divergence cannot reach a merged tree.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

__all__ = ["ProngSpec", "PRONGS", "DEFAULT_PRONGS", "ALL_PRONGS"]


@dataclasses.dataclass(frozen=True)
class ProngSpec:
    name: str
    summary: str  # one line, shown by --list-rules and pinned in README
    rules: Tuple[str, ...]  # finding rule ids this prong can emit
    default: bool  # in the default CLI set (cheap: no entry-point compiles)
    ci: str  # how tier-1 exercises it


PRONGS: Dict[str, ProngSpec] = {
    spec.name: spec
    for spec in (
        ProngSpec(
            name="ast",
            summary=(
                "AST lint over ringpop_tpu/: tick purity, dtype "
                "discipline, host-sync hygiene, donation aliasing"
            ),
            # the concrete rule list lives in astlint.ALL_RULES (it
            # carries per-rule scope/summary); these are the extras the
            # lint driver itself can emit
            rules=("syntax-error", "unreadable-file"),
            default=True,
            ci="tests/analysis/test_repo_clean.py::test_ast_prong_repo_clean",
        ),
        ProngSpec(
            name="jaxpr",
            summary=(
                "traced-graph audit of every registered entry point: "
                "callback-free scanned ticks, uint32 hash-taint discipline"
            ),
            rules=(
                "callback-primitive",
                "wide-dtype-on-hash-path",
                "trace-failure",
            ),
            default=True,
            ci=(
                "tests/analysis/test_repo_clean.py::"
                "test_jaxpr_prong_entry_points_clean"
            ),
        ),
        ProngSpec(
            name="kernels",
            summary=(
                "every pallas kernel under ops/ has a registered twin "
                "and a live gate test (toolkit.TWIN_REGISTRY)"
            ),
            rules=(
                "unregistered-kernel",
                "missing-kernel-entry",
                "missing-twin-entry",
                "missing-gate-test",
                "stale-registry-row",
            ),
            default=True,
            ci="tests/analysis/test_kernel_coverage.py",
        ),
        ProngSpec(
            name="noninterference",
            summary=(
                "dataflow slice per entry point: no obs-only input leaf "
                "(flight recorder / histograms / wavefront) reaches a "
                "trajectory output leaf"
            ),
            rules=(
                "obs-interference",
                "unclassified-state-field",
                "trace-failure",
            ),
            default=True,
            ci="tests/analysis/test_noninterference.py",
        ),
        ProngSpec(
            name="overflow",
            summary=(
                "interval-range certifier per entry point: dtype "
                "escapes, widened loop carries, index lanes vs the "
                "declared 64Mi-node / 2^20-tick envelopes"
            ),
            rules=(
                "dtype-overflow",
                "unbounded-carry",
                "index-overflow",
                "stale-allowlist",
                "trace-failure",
            ),
            default=True,  # traces (no compiles); shares the jaxpr cache
            ci="tests/analysis/test_overflow.py",
        ),
        ProngSpec(
            name="scale",
            summary=(
                "abstract per-entry memory footprint vs the per-chip "
                "HBM budget: feasible-N* ceilings pinned in "
                "SCALE_BUDGET.json"
            ),
            rules=("scale-budget", "scale-failure"),
            default=True,  # traces (no compiles); shares the jaxpr cache
            ci=(
                "tests/analysis/test_scale_budget.py + "
                "scripts/check_scale_budget.py"
            ),
        ),
        ProngSpec(
            name="donation",
            summary=(
                "donating jitted drivers compile to the committed "
                "input_output_alias surface; dropped donations are "
                "findings (DONATION_BUDGET.json)"
            ),
            rules=(
                "donation-dropped",
                "donation-budget",
                "donation-failure",
            ),
            default=False,  # compiles entry points; CI runs the cheap subset
            ci=(
                "tests/analysis/test_donation_budget.py + "
                "scripts/check_donation_budget.py"
            ),
        ),
        ProngSpec(
            name="retrace",
            summary=(
                "fresh-jit cache-count probes vs ANALYSIS_BUDGET.json "
                "(silent-retrace detector)"
            ),
            rules=("retrace-budget", "probe-failure"),
            default=False,  # compiles entry points; CI runs the cheap subset
            ci=(
                "tests/analysis/test_retrace.py + "
                "scripts/check_retrace_budget.py"
            ),
        ),
        ProngSpec(
            name="cost",
            summary=(
                "XLA static cost/memory analysis of compiled entry "
                "points vs COST_BUDGET.json (chip-free perf gate)"
            ),
            rules=("cost-budget", "cost-failure"),
            default=False,  # compiles entry points; CI runs the cheap subset
            ci=(
                "tests/analysis/test_cost_budget.py + "
                "scripts/check_cost_budget.py"
            ),
        ),
    )
}

DEFAULT_PRONGS = tuple(s.name for s in PRONGS.values() if s.default)
ALL_PRONGS = tuple(PRONGS)
