"""jaxgate prong A: ClosedJaxpr audit of the real compiled entry points.

Traces the repo's device entry points at toy shapes (n=8 — tracing only,
no compile) and walks the resulting jaxprs, recursively through ``pjit`` /
``scan`` / ``while`` / ``cond`` / ``pallas_call`` sub-jaxprs, asserting:

- **callback-primitive**: zero host-callback primitives
  (``pure_callback`` / ``io_callback`` / ``debug_callback``) anywhere, and
  doubly so inside scanned or while bodies — one callback inside the
  scanned SWIM tick both breaks the multi-chip gate-equivalence contract
  and serializes the scan on the host.
- **wide-dtype-on-hash-path**: taint-propagate from the FarmHash mixing
  constants along uint32 dataflow; any equation consuming a tainted value
  that produces a floating-point or 64-bit result breaks the mod-2^32
  arithmetic the bitwise-parity claim rests on.  ``convert_element_type``
  is deliberately NOT exempt: implicit promotions (a missing-dtype
  ``jnp.zeros``, an int64 stamp mixed into the hash state) lower to the
  same primitive as an explicit ``astype``, so the conversion itself is
  the reportable boundary.

Entry points covered (``default_entries``): the scanned full-fidelity
tick, the O(N·U) scalable tick (classic and sortless+fused-exchange
shapes), the fused checksum pipeline (both the Pallas streaming kernel
and its pure-XLA twin), the fused push-pull exchange op (Pallas kernel
and XLA twin), the farmhash block walk (scan and Pallas lowerings), and
the ring device lookup.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Iterable, List, Optional, Tuple

import numpy as np

from ringpop_tpu.analysis import dataflow
from ringpop_tpu.analysis.findings import Finding

# farmhashmk / murmur3 mixing constants — the uint32 taint seeds.  Any
# equation touching these IS the hash dataflow.
HASH_CONSTANTS = frozenset(
    {0xCC9E2D51, 0x1B873593, 0xE6546B64, 0x85EBCA6B, 0xC2B2AE35}
)

CALLBACK_PRIMITIVES = frozenset(
    {"pure_callback", "io_callback", "debug_callback", "callback"}
)

_LOOP_PRIMS = frozenset({"scan", "while"})


def _aval_dtype(var):
    aval = getattr(var, "aval", None)
    return getattr(aval, "dtype", None)


def _is_hash_const_literal(var) -> bool:
    import jax

    if not isinstance(var, jax.core.Literal):
        return False
    val = var.val
    if isinstance(val, (np.ndarray, np.generic)):
        if np.ndim(val) != 0:
            return False
        val = val.item()
    if isinstance(val, bool) or not isinstance(val, (int, float)):
        return False
    if isinstance(val, float):
        if not val.is_integer():
            return False
        val = int(val)
    return (val % (1 << 32)) in HASH_CONSTANTS


class _HashTaintVisitor(dataflow.Visitor):
    """The uint32 hash-taint discipline as a dataflow.Visitor.

    Semantics are pinned bit-for-bit to the pre-refactor recursive
    walk (tests/analysis pins findings text and count): audit-fidelity
    traversal (``precise=False`` — while/pallas boundaries conservative,
    no loop fixpoint), taint seeded from the FarmHash mixing constants,
    propagated only through int32/uint32 hops, and reported — not
    propagated — at any floating/64-bit producer.
    """

    bottom = False
    precise = False
    fixpoint = False

    def __init__(self, entry: str, findings: List[Finding]):
        self.entry = entry
        self.findings = findings

    def join(self, a: bool, b: bool) -> bool:
        return a or b

    def seed_constvar(self, var, const) -> bool:
        if isinstance(const, (np.ndarray, np.generic)) and np.ndim(const) == 0:
            v = const.item()
            return (
                isinstance(v, int)
                and not isinstance(v, bool)
                and (v % (1 << 32)) in HASH_CONSTANTS
            )
        return False

    def literal(self, lit) -> bool:
        return _is_hash_const_literal(lit)

    def enter_eqn(self, eqn, stack, in_vals) -> None:
        prim = eqn.primitive.name
        # matches every known callback primitive (CALLBACK_PRIMITIVES)
        # plus any future *_callback variant
        if "callback" not in prim:
            return
        loc = "/".join(stack) or "<top>"
        in_loop = any(
            p in _LOOP_PRIMS or p.startswith("while") for p in stack
        )
        where = (
            "inside a scanned/while body — breaks the "
            "gate-equivalence-safe tick contract"
            if in_loop
            else "in the compiled entry graph"
        )
        self.findings.append(
            Finding(
                rule="callback-primitive",
                path=f"<entry:{self.entry}>",
                line=0,
                message=f"host callback '{prim}' at {loc} {where}",
                prong="jaxpr",
            )
        )

    def eqn_out(self, eqn, stack, in_vals, subs, sub_out_vals) -> List[bool]:
        prim = eqn.primitive.name
        loc = "/".join(stack) or "<top>"
        any_tainted_in = any(in_vals)
        # map taint out of sub-jaxprs.  Positionally where the layouts
        # line up; otherwise (pallas_call kernels, while loops)
        # conservatively: if ANY inner value on the hash dataflow reaches
        # the sub-jaxpr's outputs, every output of the equation is
        # treated as tainted — dropping taint at the boundary would let
        # e.g. a Pallas-produced checksum be widened downstream unseen
        out_taint_from_subs = [False] * len(eqn.outvars)
        for sub, ot in zip(subs, sub_out_vals):
            if sub.in_map is not None:
                for i, flag in enumerate(ot[: len(eqn.outvars)]):
                    out_taint_from_subs[i] = out_taint_from_subs[i] or flag
            elif any(ot) or any_tainted_in:
                # unmapped boundary (while, pallas_call): taint born
                # inside the body OR entering it from outside can reach
                # any output — treat them all as tainted
                out_taint_from_subs = [True] * len(eqn.outvars)

        outs: List[bool] = []
        for i, ov in enumerate(eqn.outvars):
            dt = _aval_dtype(ov)
            propagate = out_taint_from_subs[i] or (
                any_tainted_in and not subs
            )
            if dt is None or not propagate:
                outs.append(False)
                continue
            kind = None
            if np.issubdtype(dt, np.floating):
                kind = f"floating ({dt})"
            elif dt in (np.dtype(np.int64), np.dtype(np.uint64)):
                # convert_element_type is NOT exempt: implicit promotions
                # lower to the same primitive as explicit astype, so an
                # exemption here would make this arm unreachable
                kind = f"64-bit ({dt})"
            if kind is not None:
                self.findings.append(
                    Finding(
                        rule="wide-dtype-on-hash-path",
                        path=f"<entry:{self.entry}>",
                        line=0,
                        message=(
                            f"'{prim}' at {loc} produces a {kind} value "
                            "from the uint32 hash dataflow — an implicit "
                            "promotion breaks mod-2^32 parity"
                        ),
                        prong="jaxpr",
                    )
                )
                outs.append(False)
            elif dt in (np.dtype(np.uint32), np.dtype(np.int32)):
                # int32 is a bit-preserving hop for mod-2^32 values —
                # dropping taint there would launder the dataflow one
                # eqn before a float widening
                outs.append(True)
            else:
                outs.append(False)
        return outs


# entry name -> (ClosedJaxpr, output shape pytree).  A registered entry
# is traced ONCE per process and shared between the jaxpr prong and the
# noninterference slicer (both walk the same registry; without this a
# default CLI run paid every multi-second scanned-tick trace twice).
# Keyed by REGISTRY name only — ad-hoc audits (audit_fn, doctored
# mutation entries) never touch the cache.
_TRACE_CACHE: dict = {}


def trace_entry(name: str, fn: Callable, args: Tuple):
    """(ClosedJaxpr, out-shape pytree) for a registered entry, cached."""
    import jax

    hit = _TRACE_CACHE.get(name)
    if hit is None:
        hit = jax.make_jaxpr(fn, return_shape=True)(*args)
        _TRACE_CACHE[name] = hit
    return hit


def clear_trace_cache() -> None:
    _TRACE_CACHE.clear()


def _audit_closed(name: str, closed) -> List[Finding]:
    findings: List[Finding] = []
    visitor = _HashTaintVisitor(name, findings)
    dataflow.walk(
        closed.jaxpr,
        closed.consts,
        (),
        [False] * len(closed.jaxpr.invars),
        visitor,
    )
    return findings


def _trace_failure(name: str, e: Exception) -> Finding:
    return Finding(
        rule="trace-failure",
        path=f"<entry:{name}>",
        line=0,
        message=f"entry point failed to trace: {type(e).__name__}: {e}",
        prong="jaxpr",
    )


def audit_fn(
    name: str, fn: Callable, args: Tuple
) -> List[Finding]:
    """Trace ``fn(*args)`` and audit the resulting ClosedJaxpr."""
    import jax

    try:
        closed = jax.make_jaxpr(fn)(*args)
    except Exception as e:  # a broken entry point is itself a finding
        return [_trace_failure(name, e)]
    return _audit_closed(name, closed)


# ---------------------------------------------------------------------------
# entry-point registry


@dataclasses.dataclass(frozen=True)
class EntryPoint:
    name: str
    build: Callable[[], Tuple[Callable, Tuple]]  # () -> (fn, args)


def _toy_universe(n: int = 8):
    from ringpop_tpu.ops import checksum_encode as ce

    return ce.Universe.from_addresses(
        [f"10.0.0.{i}:3000" for i in range(n)]
    )


def _sim_setup(
    n: int = 8,
    flight_recorder: bool = False,
    histograms: bool = False,
    fused_tick: str = "off",
):
    import jax

    from ringpop_tpu.models.sim import engine

    universe = _toy_universe(n)
    # fused_tick defaults to the pinned CLASSIC shape so the base
    # entries stay comparable with the pre-round-16 manifests; the
    # -fused entries pin the xla twin explicitly (pallas is covered at
    # the op level, exchange-pallas style)
    params = engine.SimParams(
        n=n,
        hash_impl="scan",
        flight_recorder=flight_recorder,
        event_capacity=256 if flight_recorder else 65536,
        histograms=histograms,
        fused_tick=fused_tick,
    )
    params = engine.resolve_auto_parity(params, jax.default_backend())
    state = engine.init_state(params, seed=0, universe=universe)
    return engine, params, universe, state


def _entry_engine_tick_scan(
    flight_recorder: bool = False,
    histograms: bool = False,
    fused_tick: str = "off",
) -> Tuple[Callable, Tuple]:
    import jax
    import jax.numpy as jnp

    engine, params, universe, state = _sim_setup(
        8,
        flight_recorder=flight_recorder,
        histograms=histograms,
        fused_tick=fused_tick,
    )
    n, t = 8, 2
    inputs = engine.TickInputs(
        kill=jnp.zeros((t, n), bool),
        revive=jnp.zeros((t, n), bool),
        join=jnp.zeros((t, n), bool),
        partition=jnp.full((t, n), -1, jnp.int32),
    )

    def scanned(state, inputs):
        def body(st, inp):
            return engine.tick(st, inp, params, universe)

        return jax.lax.scan(body, state, inputs)

    return scanned, (state, inputs)


def _entry_engine_scalable_tick(
    wavefront: bool = False,
    perm_impl: str = "auto",
    fused_exchange: str = "auto",
    histograms: bool = False,
    exchange_metrics: int = 0,
) -> Tuple[Callable, Tuple]:
    from ringpop_tpu.models.sim import engine_scalable as es

    params = es.ScalableParams(
        n=8,
        u=128,
        wavefront=wavefront,
        perm_impl=perm_impl,
        fused_exchange=fused_exchange,
        histograms=histograms,
        exchange_metrics=exchange_metrics,
    )
    state = es.init_state(params, seed=0)
    inputs = es.ChurnInputs.quiet(8)

    def one(state, inputs):
        return es.tick(state, inputs, params)

    return one, (state, inputs)


def _exchange_args(n: int = 8, w: int = 4, seed: int = 3):
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(seed)

    def u32(shape):
        return jnp.asarray(
            rng.integers(0, 2**32, size=shape, dtype=np.uint32)
        )

    return u32((n, w)), u32((n, w)), u32((n, w)), u32((w * 32,))


def _entry_exchange(impl: str) -> Tuple[Callable, Tuple]:
    """The fused push-pull exchange op (ops.exchange) — both the Pallas
    megakernel (traced in interpret-free form; tracing never compiles)
    and its bit-exact pure-XLA twin must stay callback-free with the
    whole delta path in uint32 lanes."""
    from ringpop_tpu.ops import exchange as exch

    def fused(heard, pulled, pushed, r_delta):
        return exch.exchange(heard, pulled, pushed, r_delta, impl=impl)

    return fused, _exchange_args()


def _entry_exchange_local() -> Tuple[Callable, Tuple]:
    """The shard-local fused exchange (ops.exchange.exchange_local):
    the inside-shard_map entry the mesh plane pins — same mod-2^32
    contract as the global op, no auto resolution, counts never
    requested."""
    from ringpop_tpu.ops import exchange as exch

    def local(heard, pulled, pushed, r_delta):
        return exch.exchange_local(
            heard, pulled, pushed, r_delta, impl="xla"
        )

    return local, _exchange_args()


def _plane_fixture(n: int = 8, metrics: bool = False):
    """1-device mesh + exchange plane at toy shapes — the mesh axis is
    logical (shard_map traces identically at any device count), so the
    entries run under both the 1-device CLI env and the 8-device test
    conftest."""
    from ringpop_tpu.parallel import mesh as pmesh

    mesh = pmesh.make_mesh(1)
    return pmesh.make_exchange_plane(mesh, "xla", n=n, metrics=metrics)


def _plane_args(n: int = 8, w: int = 4, seed: int = 3, metrics: bool = False):
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(seed)
    heard, _pull, _push, r_delta = _exchange_args(n, w, seed)
    perm = rng.permutation(n).astype(np.int32)
    args = (
        heard,
        r_delta,
        jnp.asarray(
            rng.integers(0, 2**32, size=w, dtype=np.uint32)
        ),  # active_words
        jnp.asarray(rng.random(n) < 0.7),  # direct_ok
        jnp.asarray(perm),  # partner0
        jnp.asarray(np.argsort(perm).astype(np.int32)),  # inv_base
    )
    if not metrics:
        return args
    from ringpop_tpu.ops import exchange as exch

    # the round-17 telemetry plane threads the [S, ...] counter and
    # histogram planes through the shard_map body (S=1 here)
    return args + (
        exch.init_exchange_counters(1),
        exch.init_exchange_hist(1),
    )


def _entry_exchange_plane() -> Tuple[Callable, Tuple]:
    """The round-14 shard_map'd exchange plane: explicit all_to_all /
    all-gather partner-row routing + the fused kernel on shard-local
    tiles.  The collectives are device primitives, not callbacks, and
    the delta path must stay in uint32 lanes through the routing."""
    plane = _plane_fixture()

    def fn(heard, r_delta, active_words, ok, fwd, inv):
        return plane(heard, r_delta, active_words, ok, fwd, inv)

    return fn, _plane_args()


def _entry_exchange_plane_metrics() -> Tuple[Callable, Tuple]:
    """The round-17 telemetry-carrying plane flavor: same routing and
    fused kernel as ``exchange-plane`` plus the write-only counter /
    histogram bumps — the bumps live INSIDE the shard_map body, so they
    must hold the same callback-free / uint32 gates (one float sneaking
    into the cap-utilization log2 pricing would surface here)."""
    plane = _plane_fixture(metrics=True)

    def fn(heard, r_delta, active_words, ok, fwd, inv, exch_c, exch_h):
        return plane(heard, r_delta, active_words, ok, fwd, inv, exch_c, exch_h)

    return fn, _plane_args(metrics=True)


def _entry_engine_scalable_tick_shardmap(
    metrics: bool = False,
) -> Tuple[Callable, Tuple]:
    """The sharded storm tick with the exchange seam filled by the
    shard_map plane — the program ShardedStorm compiles under a mesh
    (ISSUE 10 acceptance: the sharded tick holds the same callback-free
    / uint32 discipline as every single-device shape).  ``metrics=True``
    pairs the telemetry-carrying plane with
    ``ScalableParams.exchange_metrics`` — the shape ShardedStorm
    actually compiles when the mesh observatory is on, and the entry the
    noninterference prong slices to prove the counter planes never
    reach the trajectory."""
    from ringpop_tpu.models.sim import engine_scalable as es

    params = es.ScalableParams(
        n=8,
        u=128,
        perm_impl="sortless",
        fused_exchange="xla",
        exchange_metrics=1 if metrics else 0,
    )
    plane = _plane_fixture(metrics=metrics)
    state = es.init_state(params, seed=0)
    inputs = es.ChurnInputs.quiet(8)

    def one(state, inputs):
        return es.tick(state, inputs, params, exchange_plane=plane)

    return one, (state, inputs)


def _fused_args(n: int = 8, b: int = 4, seed: int = 0):
    import jax.numpy as jnp
    import numpy as np

    universe = _toy_universe(n)
    rng = np.random.default_rng(seed)
    present = jnp.asarray(rng.random((b, n)) < 0.8)
    status = jnp.asarray(rng.integers(0, 4, size=(b, n)), dtype=jnp.int32)
    # int32 epoch stamps: x64 stays off in tests, so int64 ms values
    # would silently truncate anyway — digit-count coverage is identical
    inc = jnp.asarray(
        rng.integers(1, 2**31 - 1, size=(b, n)), dtype=jnp.int32
    )
    return universe, present, status, inc


def _entry_fused_checksum(impl: str) -> Tuple[Callable, Tuple]:
    from ringpop_tpu.ops import fused_checksum as fc

    universe, present, status, inc = _fused_args()

    def fused(present, status, inc):
        return fc.membership_checksums(
            universe, present, status, inc, impl=impl
        )

    return fused, (present, status, inc)


def _farmhash_args(b: int = 8, width: int = 64):
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(1)
    mat = jnp.asarray(
        rng.integers(0, 256, size=(b, width)), dtype=jnp.uint8
    )
    lens = jnp.asarray(
        rng.integers(0, width - 4, size=(b,)), dtype=jnp.int32
    )
    return mat, lens


def _entry_farmhash(impl: str) -> Tuple[Callable, Tuple]:
    from ringpop_tpu.ops import jax_farmhash as jfh

    mat, lens = _farmhash_args()

    def hash_rows(mat, lens):
        return jfh.hash32_rows(mat, lens, impl=impl)

    return hash_rows, (mat, lens)


def _ring_fn() -> Callable:
    """build_ring + lookup + lookup_n composition — the single
    definition shared by the jaxpr entry and the retrace probe."""
    from ringpop_tpu.models.ring import device

    def ring_lookup(table, mask, key_hash):
        ring = device.build_ring(table, mask)
        n_points = device.ring_size(mask, table.shape[1])
        one = device.lookup(ring, n_points, key_hash)
        many = device.lookup_n(ring, n_points, key_hash, 3)
        return one, many

    return ring_lookup


def _ring_args(n: int = 8, seed: int = 2) -> Tuple:
    import jax.numpy as jnp
    import numpy as np

    rng = np.random.default_rng(seed)
    table = jnp.asarray(
        rng.integers(0, 2**32, size=(n, 100), dtype=np.uint32)
    )
    mask = jnp.asarray(rng.random(n) < 0.75)
    key_hash = jnp.uint32(rng.integers(0, 2**32))
    return table, mask, key_hash


def _entry_ring_device() -> Tuple[Callable, Tuple]:
    return _ring_fn(), _ring_args()


def _entry_route_lookup_batched() -> Tuple[Callable, Tuple]:
    """The batched fixed-width successor lookup
    (route.ring_kernel.lookup_n_fixed): the static-trip vmapped twin of
    device.lookup_n's while-loop walk — the serving-path shape, so it
    holds the same purity/dtype gates.  width=6 deliberately avoids a
    multiple of the toy n so the scale certifier keeps the successor
    window constant while the ring and the query batch scale."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.models.ring import device
    from ringpop_tpu.models.route import ring_kernel

    table, mask, _ = _ring_args()
    rng = np.random.default_rng(5)
    keys = jnp.asarray(rng.integers(0, 2**32, size=16, dtype=np.uint32))

    def batched(table, mask, keys):
        ring = device.build_ring(table, mask)
        n_points = device.ring_size(mask, table.shape[1])
        return jax.vmap(
            lambda k: ring_kernel.lookup_n_fixed(ring, n_points, k, 3, 6)
        )(keys)

    return batched, (table, mask, keys)


def _route_fixture(
    impl: str,
    n: int = 8,
    r: int = 4,
    seed: int = 4,
    histograms: bool = False,
    reqtrace: bool = False,
):
    """Small routing-plane fixture shared by the route-tick entries and
    the retrace probe: buckets/reps/cdf constants + one RouteState."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.models.ring import device as ringdev
    from ringpop_tpu.models.route import plane, ring_kernel, traffic

    params = plane.RouteParams(
        n=n,
        replica_points=r,
        bucket_bits=2,
        queries_per_tick=16,
        key_space=64,
        ring_impl=impl,
        max_changed=4,
        max_dirty=4,
        histograms=histograms,
        reqtrace=reqtrace,
        req_capacity=64,
        req_sample_log2=1,
    )
    reps_np = np.asarray(ringdev.device_replica_hashes(n, r))
    buckets = ring_kernel.build_buckets(reps_np, params.bucket_bits)
    reps = jnp.asarray(reps_np)
    cdf = traffic.zipf_cdf(params.key_space, params.zipf_s)
    rng = np.random.default_rng(seed)
    mask0 = jnp.asarray(rng.random(n) < 0.9)
    state = plane.init_route_state(params, buckets, reps, mask0, seed=seed)
    in_ring = jnp.asarray(rng.random(n) < 0.8)
    proc_alive = jnp.asarray(rng.random(n) < 0.9)
    checksums = jnp.asarray(
        rng.integers(0, 2**32, size=n, dtype=np.uint32)
    )
    return plane, params, buckets, reps, cdf, state, (
        in_ring, proc_alive, checksums,
    )


def _entry_route_tick(
    impl: str, histograms: bool = False, reqtrace: bool = False
) -> Tuple[Callable, Tuple]:
    """The routing plane's scanned tick (ISSUE 6): Zipf traffic draw,
    bucketed/sort-twin ring refresh, batched lookups and the misroute/
    keys-diverged/checksum-reject counters must all stay callback-free
    with the ring-key dataflow in integer lanes."""
    plane, params, buckets, reps, cdf, state, dyn = _route_fixture(
        impl, histograms=histograms, reqtrace=reqtrace
    )

    def one(state, in_ring, proc_alive, checksums):
        return plane.route_tick(
            state, buckets, reps, cdf, in_ring, proc_alive, checksums,
            params,
        )

    return one, (state,) + dyn


def _entry_route_ring_incremental() -> Tuple[Callable, Tuple]:
    """The incremental ring-maintenance kernel in isolation: dirty-
    bucket re-merge + lookup on the bucketed layout."""
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.models.route import ring_kernel as rk

    plane, params, buckets, reps, cdf, state, dyn = _route_fixture(
        "incremental"
    )
    in_ring = dyn[0]
    rng = np.random.default_rng(6)
    keys = jnp.asarray(rng.integers(0, 2**32, size=16, dtype=np.uint32))

    def one(rstate, in_ring, keys):
        st, n_changed, n_dirty, ov = rk.update(
            buckets, rstate, in_ring, max_changed=4, max_dirty=4
        )
        return rk.lookup(st, keys), rk.materialize(st, 8 * 4), n_changed

    return one, (state.ring, in_ring, keys)


def _fuzz_fixture(engine_name: str, b: int = 2, t: int = 2, seed0: int = 0):
    """Tiny batched-fuzz fixture shared by the jaxpr entries and the
    retrace probe: B stacked instances + [T, B, N] dense fault planes."""
    from ringpop_tpu.fuzz import executor as fex
    from ringpop_tpu.fuzz import scenarios as fsc

    cfg = fsc.ScenarioConfig(engine=engine_name, n=8, ticks=t)
    ex = fex.executor_for(cfg)
    states = fex._stack_states(
        [ex._init_state(seed0 + s) for s in range(b)]
    )
    scheds = [fsc._blank_schedule(cfg) for _ in range(b)]
    inputs = fex._stack_inputs([s.as_inputs() for s in scheds])
    return ex, states, inputs


def _entry_fuzz_scan_full() -> Tuple[Callable, Tuple]:
    from ringpop_tpu.fuzz import executor as fex

    ex, states, inputs = _fuzz_fixture("full")

    def scan(states, inputs):
        return fex.scenario_scan_full(
            states, inputs, ex.params, ex.universe
        )

    return scan, (states, inputs)


def _entry_fuzz_scan_scalable() -> Tuple[Callable, Tuple]:
    from ringpop_tpu.fuzz import executor as fex

    ex, states, inputs = _fuzz_fixture("scalable")

    def scan(states, inputs):
        return fex.scenario_scan_scalable(states, inputs, ex.params)

    return scan, (states, inputs)


def _entry_checkpoint_restore() -> Tuple[Callable, Tuple]:
    """The recovery plane's post-load fixup (cluster.fixup_sim_state)
    with fused_checksum="on" — the one device computation between
    checkpoint bytes and a resuming engine (record-cache rebuild via
    member_records), so it must hold the same purity/dtype gates as
    the tick it hands the state to."""
    import jax

    from ringpop_tpu.models.sim import cluster, engine

    universe = _toy_universe(8)
    params = engine.SimParams(n=8, hash_impl="scan", fused_checksum="on")
    params = engine.resolve_auto_parity(params, jax.default_backend())
    state = engine.init_state(params, seed=0, universe=universe)

    def restore(state):
        return cluster.fixup_sim_state(state, params, universe)

    return restore, (state,)


def _fused_apply_args(n: int = 8, seed: int = 0):
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.ops import fused_apply as fap

    rng = np.random.default_rng(seed)

    def bpl(p):
        return jnp.asarray(rng.random((n, n)) < p)

    def ipl(lo, hi):
        return jnp.asarray(rng.integers(lo, hi, (n, n)), dtype=jnp.int32)

    st = fap.ApplyState(
        bpl(0.8), ipl(0, 4), ipl(0, 20), bpl(0.3), ipl(0, 4),
        ipl(0, 20), ipl(-1, n), ipl(0, 20), ipl(0, 9), ipl(-1, 30),
    )
    return st, bpl(0.4), ipl(0, 4), ipl(0, 20), ipl(0, n), ipl(0, 20)


def _entry_fused_apply(impl: str) -> Tuple[Callable, Tuple]:
    """The round-16 fused membership-update op (ops.fused_apply): both
    lowerings must stay callback-free with integer dataflow discipline."""
    import jax.numpy as jnp

    from ringpop_tpu.ops import fused_apply as fap
    from ringpop_tpu.ops import toolkit

    st, recv, us, ui, usrc, usi = _fused_apply_args()
    n = st.status.shape[0]
    union = jnp.zeros((n, toolkit.packed_width(n)), jnp.uint32)

    def op(st, recv, us, ui, usrc, usi, union):
        return fap.apply_updates(
            st, recv, us, ui, usrc, usi, jnp.int32(5), jnp.int32(9),
            union, impl=impl, want_masks=True, want_count=True,
        )

    return op, (st, recv, us, ui, usrc, usi, union)


def _entry_fused_piggyback(impl: str) -> Tuple[Callable, Tuple]:
    """The round-16 fused dissemination-budget op (ops.fused_piggyback)."""
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.ops import fused_piggyback as fpb

    n = 8
    rng = np.random.default_rng(2)
    active = jnp.asarray(rng.random((n, n)) < 0.5)
    pb = jnp.asarray(rng.integers(0, 9, (n, n)), dtype=jnp.int32)
    nbump = jnp.asarray(rng.integers(0, 3, n), dtype=jnp.int32)
    max_pb = jnp.asarray(rng.integers(4, 16, n), dtype=jnp.int32)
    hits = jnp.asarray(rng.integers(0, 2, (n, n)), dtype=jnp.int32)

    def op(active, pb, nbump, max_pb, hits):
        return fpb.pb_budget(active, pb, nbump, max_pb, hits, impl=impl)

    return op, (active, pb, nbump, max_pb, hits)


DEFAULT_ENTRIES: List[EntryPoint] = [
    EntryPoint("engine-tick-scan", _entry_engine_tick_scan),
    # the round-16 fused full-fidelity tick: the scanned tick with the
    # apply/piggyback sites routed through the toolkit's fused ops must
    # hold the same purity / dtype gates as the classic shape
    EntryPoint(
        "engine-tick-scan-fused",
        lambda: _entry_engine_tick_scan(fused_tick="xla"),
    ),
    EntryPoint("fused-apply-xla", lambda: _entry_fused_apply("xla")),
    EntryPoint(
        "fused-apply-pallas", lambda: _entry_fused_apply("pallas")
    ),
    EntryPoint(
        "fused-piggyback-xla", lambda: _entry_fused_piggyback("xla")
    ),
    EntryPoint(
        "fused-piggyback-pallas",
        lambda: _entry_fused_piggyback("pallas"),
    ),
    # the flight-recorder-enabled scanned tick MUST stay callback-free:
    # the whole point of the device-side recorder is event telemetry
    # without host round-trips in the scan (ISSUE 4 acceptance)
    EntryPoint(
        "engine-tick-scan-flight-recorder",
        lambda: _entry_engine_tick_scan(flight_recorder=True),
    ),
    # the round-15 performance observatory: the latency-histogram-
    # enabled scanned ticks must stay callback-free (the whole point of
    # device-side histograms is percentile telemetry without host
    # round-trips) with the hash dataflow in uint32 lanes
    EntryPoint(
        "engine-tick-scan-histograms",
        lambda: _entry_engine_tick_scan(histograms=True),
    ),
    EntryPoint("engine-scalable-tick", _entry_engine_scalable_tick),
    EntryPoint(
        "engine-scalable-tick-wavefront",
        lambda: _entry_engine_scalable_tick(wavefront=True),
    ),
    EntryPoint(
        "engine-scalable-tick-histograms",
        lambda: _entry_engine_scalable_tick(histograms=True),
    ),
    # the round-10 hot-path rewrite: the sortless-PRP + fused-exchange
    # tick must hold the same purity/uint32 gates as the classic shape
    EntryPoint(
        "engine-scalable-tick-fused",
        lambda: _entry_engine_scalable_tick(
            perm_impl="sortless", fused_exchange="xla"
        ),
    ),
    EntryPoint("exchange-xla", lambda: _entry_exchange("xla")),
    EntryPoint("exchange-pallas", lambda: _entry_exchange("pallas")),
    # the round-14 explicitly-collective programs: the shard_map'd
    # exchange plane and the sharded storm tick built on it — the first
    # collective entry points in the repo, held to the same gates
    EntryPoint("exchange-plane", _entry_exchange_plane),
    EntryPoint(
        "engine-scalable-tick-shardmap",
        _entry_engine_scalable_tick_shardmap,
    ),
    # the round-17 mesh observatory: the telemetry-carrying plane, the
    # sharded tick compiled around it, and the single-device analytic
    # twin (exchange_metrics without a plane) all hold the same gates —
    # instrumentation must not buy its visibility with a callback or a
    # widened hash lane
    EntryPoint(
        "exchange-plane-metrics", _entry_exchange_plane_metrics
    ),
    EntryPoint(
        "engine-scalable-tick-shardmap-metrics",
        lambda: _entry_engine_scalable_tick_shardmap(metrics=True),
    ),
    EntryPoint(
        "engine-scalable-tick-exchange-metrics",
        lambda: _entry_engine_scalable_tick(
            perm_impl="sortless",
            fused_exchange="xla",
            exchange_metrics=4,
        ),
    ),
    EntryPoint("fused-checksum-xla", lambda: _entry_fused_checksum("xla")),
    EntryPoint(
        "fused-checksum-pallas", lambda: _entry_fused_checksum("pallas")
    ),
    EntryPoint("farmhash-scan", lambda: _entry_farmhash("scan")),
    EntryPoint(
        "farmhash-pallas-nogrid",
        lambda: _entry_farmhash("pallas_nogrid"),
    ),
    EntryPoint("ring-device-lookup", _entry_ring_device),
    # the round-11 routing plane: both ring impls of the routing tick
    # (incremental bucketed + full-sort twin) and the maintenance kernel
    # alone hold the same purity gates
    EntryPoint(
        "route-tick-incremental",
        lambda: _entry_route_tick("incremental"),
    ),
    EntryPoint("route-tick-full", lambda: _entry_route_tick("full")),
    EntryPoint(
        "route-tick-histograms",
        lambda: _entry_route_tick("incremental", histograms=True),
    ),
    # round-19 request observatory: the sampled per-request trace buffer
    # rides the same tick; its masked cumsum-scatter append and the
    # sampled-subset counters must hold the purity gates and the
    # noninterference prong must prove the req_* plane write-only
    EntryPoint(
        "route-tick-reqtrace",
        lambda: _entry_route_tick("incremental", reqtrace=True),
    ),
    EntryPoint(
        "route-ring-incremental", _entry_route_ring_incremental
    ),
    # the round-12 scenario fuzzer: both engines' vmapped scanned ticks
    # (per-instance state AND per-instance fault schedules) must stay
    # callback-free with the hash dataflow in uint32 lanes — every fuzz
    # sweep and every shrink candidate batch runs through these
    EntryPoint("fuzz-scenario-scan-full", _entry_fuzz_scan_full),
    EntryPoint(
        "fuzz-scenario-scan-scalable", _entry_fuzz_scan_scalable
    ),
    # round-18 scale certifier: the entry points added since PR 12 that
    # the prongs were not yet seeing — the shard-local exchange the
    # mesh plane pins, the batched serving-path ring lookup, and the
    # checkpoint-restore fixup (the only device computation between
    # saved bytes and a resuming engine)
    EntryPoint("exchange-local-xla", _entry_exchange_local),
    EntryPoint("route-lookup-batched", _entry_route_lookup_batched),
    EntryPoint("checkpoint-restore", _entry_checkpoint_restore),
]


def audit_entries(
    entries: Optional[Iterable[EntryPoint]] = None,
) -> List[Finding]:
    registry = entries is None
    out: List[Finding] = []
    for ep in DEFAULT_ENTRIES if registry else entries:
        try:
            fn, args = ep.build()
        except Exception as e:
            out.append(
                Finding(
                    rule="trace-failure",
                    path=f"<entry:{ep.name}>",
                    line=0,
                    message=(
                        f"entry point setup failed: {type(e).__name__}: {e}"
                    ),
                    prong="jaxpr",
                )
            )
            continue
        if not registry:
            out.extend(audit_fn(ep.name, fn, args))
            continue
        try:
            closed, _ = trace_entry(ep.name, fn, args)
        except Exception as e:
            out.append(_trace_failure(ep.name, e))
            continue
        out.extend(_audit_closed(ep.name, closed))
    return out
