"""jaxgate prong B: AST lint over ``ringpop_tpu/``.

The rules encode the repo's device-path conventions as syntax checks:

==================  =====================================================
rule                invariant
==================  =====================================================
host-coerce         no ``float()/int()/bool()/.item()`` on traced values
                    inside jit contexts (host sync / TracerConversion)
np-on-traced        no ``np.asarray/np.prod/np.sum/...`` on traced values
                    inside jit contexts (silent device->host transfer)
implicit-dtype      ``jnp.array/zeros/ones/full/empty/arange`` in ``ops/``
                    and ``models/sim/`` must pass an explicit dtype (the
                    x64-flag-dependent default breaks uint32 discipline)
implicit-accum-     ``jnp.sum/cumsum/prod/cumprod`` in the same paths must
dtype               make the accumulator dtype reviewable at the call site
                    — a ``dtype=`` kwarg or an ``.astype(...)``-pinned
                    operand (ISSUE 18: int32 telemetry accumulators are
                    what the interval certifier overflow-prices at the
                    declared 64Mi-node scale)
py-random-time      no ``random``/``time``/``np.random`` calls inside jit
                    contexts (trace-time nondeterminism baked into the
                    compiled program)
mutable-default     no mutable / array-valued default arguments
block-until-ready   ``block_until_ready`` only in obs (device sync in
                    library code serializes the dispatch pipeline)
callback-in-device  no ``io_callback/pure_callback/debug_callback`` or
                    ``jax.debug.print`` in device modules (the scanned
                    tick must stay gate-equivalence-safe)
stale-ref-across-   no bare ``x = self.state`` binding read after the
donation            state was passed to a donating dispatch — the exact
                    PR-7/PR-8 aliasing hazard (donated buffers are dead;
                    ``device_get``/``host_copy_states`` first)
assert-on-traced    no ``assert`` over traced values inside jit contexts
                    (trace-time only; raises on a concrete tracer)
==================  =====================================================

Jit contexts — where the traced-value rules apply — are inferred per
module: functions decorated with / passed to ``jax.jit`` or ``jax.lax``
control flow, functions named in :data:`TRACED_ENTRIES` (entry points
jitted from *other* modules), every ``def`` nested inside a jit context,
and (to a fixpoint) every module-level function called by name from one.
A ``# jaxgate: host`` comment on the ``def`` line opts a trace-time host
helper out (e.g. a static-table builder invoked during tracing).

Traced values are approximated by local taint: function parameters and
``jnp``/``lax`` call results, propagated through assignments.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Set, Tuple

from ringpop_tpu.analysis import findings as fmod
from ringpop_tpu.analysis.findings import Finding

# Entry points jitted from other modules (cluster.py, mesh.py, the bench):
# module suffix -> function names to treat as jit roots.
TRACED_ENTRIES: Dict[str, Set[str]] = {
    "models/sim/engine.py": {"tick", "compute_checksums"},
    "models/sim/flight.py": {"append_events", "record_tick_events"},
    "models/sim/engine_scalable.py": {
        "tick",
        "compute_checksums",
        "farmhash_truth_checksum",
    },
    "ops/jax_farmhash.py": {"hash32_rows"},
    "ops/exchange.py": {"exchange", "exchange_xla", "exchange_local"},
    # the round-14 shard_map'd exchange plane: the plane body and its
    # row-routing helper are the repo's first explicitly-collective
    # traced code (all_to_all / all_gather / ppermute-class primitives)
    "parallel/mesh.py": {
        "make_exchange_plane",
        "_route_rows",
        "_route_rows_stats",
    },
    "ops/fused_checksum.py": {"membership_checksums", "fused_hash_rows"},
    # the round-16 kernel toolkit + fused full-tick ops: the shared
    # row-streaming scaffold and both fused sites are traced from
    # engine.tick and from the audit/gate harnesses
    "ops/toolkit.py": {"stream_row_tiles", "pack_bool_rows"},
    "ops/fused_apply.py": {"apply_updates", "apply_updates_xla"},
    "ops/fused_piggyback.py": {"pb_budget", "pb_budget_xla"},
    "ops/checksum_encode.py": {"membership_rows", "ring_rows"},
    "ops/pallas_farmhash.py": {
        "block_loop",
        "block_loop_nogrid",
        "fused_stream_nogrid",
        "fused_stream_xla",
    },
    "ops/record_mix.py": {"record_mix"},
    # the round-19 sampled request-trace plane: appended from route_tick
    # inside the routed scan
    "models/route/reqtrace.py": {
        "sample_mask",
        "record_tick_requests",
        "append_requests",
    },
    # the round-15 device histogram primitives: called from every
    # histogram-enabled tick (both engines + the routing plane)
    "ops/histogram.py": {"init", "bucket_index", "record", "record_count"},
    "models/ring/device.py": {
        "build_ring",
        "lookup",
        "lookup_n",
        "device_replica_hashes",
        "ring_checksum",
    },
    "models/route/ring_kernel.py": {
        "full_rebuild",
        "update",
        "materialize",
        "lookup",
        "lookup_n_fixed",
        "dirty_stats",
    },
    "models/route/traffic.py": {"sample_keys", "key_hashes", "zipf_cdf"},
    "models/route/plane.py": {"route_tick", "init_route_state"},
    # the fuzz executors' vmapped scanned ticks (ISSUE 7): jitted from
    # the executor classes and the scenario sweep driver
    "fuzz/executor.py": {"scenario_scan_full", "scenario_scan_scalable"},
}

# Device modules: code on (or feeding) the compiled path.
DEVICE_PATHS = (
    "ops/",
    "models/sim/",
    "models/ring/",
    "models/route/",
    "parallel/",
)
# Paths where implicit-dtype applies (ISSUE: constructors feeding the
# uint32 hash dataflow and the scanned tick state).
DTYPE_PATHS = ("ops/", "models/sim/", "models/route/")
# block_until_ready is legitimate in observability / bench plumbing.
SYNC_OK_PATHS = ("obs/",)

_JIT_WRAPPERS = {"jit", "pjit", "vmap", "pmap", "shard_map", "named_call"}
_LAX_CONSUMERS = {
    "scan",
    "while_loop",
    "fori_loop",
    "cond",
    "switch",
    "map",
    "associative_scan",
    "custom_root",
}
_COERCERS = {"int", "float", "bool", "complex"}
_NP_HOST_FUNCS = {
    "asarray",
    "array",
    "prod",
    "sum",
    "any",
    "all",
    "max",
    "min",
    "mean",
}
# constructors whose DEFAULT dtype depends on the x64 flag / weak-type
# promotion.  jnp.asarray is deliberately absent: it is the host->device
# upload idiom and preserves the (concrete) numpy dtype; 64-bit uploads
# into the hash dataflow are the jaxpr prong's job.
_DTYPE_CTORS = {"array", "zeros", "ones", "full", "empty", "arange"}
# positional index at which each constructor accepts dtype
_DTYPE_POS = {
    "array": 1,
    "zeros": 1,
    "ones": 1,
    "empty": 1,
    "full": 2,
    "arange": 3,
}
_CALLBACK_NAMES = {"io_callback", "pure_callback", "debug_callback"}


def _attr_chain(node: ast.AST) -> Optional[str]:
    """Dotted name of a Name/Attribute chain ('jax.lax.scan'), else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


# attribute reads that yield static (trace-time) metadata, not traced
# values: names reached only through these do not carry taint
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "aval"}


def _names_in(node: ast.AST) -> Set[str]:
    """Names referenced by ``node``, excluding those reached only through
    static-metadata attributes (``x.shape[0]`` is host math, not a trace)."""
    out: Set[str] = set()

    def visit(n: ast.AST) -> None:
        if isinstance(n, ast.Attribute) and n.attr in _STATIC_ATTRS:
            return
        if isinstance(n, ast.Name):
            out.add(n.id)
        for child in ast.iter_child_nodes(n):
            visit(child)

    visit(node)
    return out


def _own_nodes(fn: ast.AST) -> Iterator[ast.AST]:
    """Walk ``fn``'s body, stopping at nested function boundaries: nested
    defs are jit contexts of their own and get their own rule pass (one
    finding per violation, not one per enclosing context)."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class ModuleInfo:
    """Parsed module + shared analyses consumed by the rules."""

    def __init__(self, path: Path, rel: str, source: str):
        self.path = path
        self.rel = rel  # relative to the package root's parent (posix)
        self.source = source
        self.tree = ast.parse(source, filename=str(path))
        self.lines = source.splitlines()
        self.suppressions = fmod.parse_suppressions(source)
        self.host_lines = fmod.host_marked_lines(source)
        self._parents: Dict[ast.AST, ast.AST] = {}
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
        self.functions: List[ast.AST] = [
            n
            for n in ast.walk(self.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda))
        ]
        self.jit_contexts: Set[ast.AST] = self._infer_jit_contexts()
        self._taint_cache: Dict[ast.AST, Set[str]] = {}

    # -- jit-context inference ------------------------------------------

    def _is_host_marked(self, fn: ast.AST) -> bool:
        return getattr(fn, "lineno", 0) in self.host_lines

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(
                cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                return cur
            cur = self._parents.get(cur)
        return None

    def _decorated_jit(self, fn: ast.AST) -> bool:
        for dec in getattr(fn, "decorator_list", []):
            target = dec.func if isinstance(dec, ast.Call) else dec
            chain = _attr_chain(target) or ""
            leaf = chain.rsplit(".", 1)[-1]
            if leaf in _JIT_WRAPPERS:
                return True
            if leaf == "partial" and isinstance(dec, ast.Call) and dec.args:
                inner = _attr_chain(dec.args[0]) or ""
                if inner.rsplit(".", 1)[-1] in _JIT_WRAPPERS:
                    return True
        return False

    def _infer_jit_contexts(self) -> Set[ast.AST]:
        by_name: Dict[str, List[ast.AST]] = {}
        module_level: Dict[str, ast.AST] = {}
        for fn in self.functions:
            name = getattr(fn, "name", None)
            if name:
                by_name.setdefault(name, []).append(fn)
                if isinstance(self._parents.get(fn), ast.Module):
                    module_level[name] = fn

        roots: Set[ast.AST] = set()
        # 1. decorator-jitted
        for fn in self.functions:
            if self._decorated_jit(fn):
                roots.add(fn)
        # 2. configured cross-module entry points
        for suffix, names in TRACED_ENTRIES.items():
            if self.rel.endswith(suffix):
                for name in names:
                    roots.update(by_name.get(name, []))
        # 3. function names passed to jax.jit / lax control flow
        for call in ast.walk(self.tree):
            if not isinstance(call, ast.Call):
                continue
            chain = _attr_chain(call.func) or ""
            leaf = chain.rsplit(".", 1)[-1]
            if leaf not in (_JIT_WRAPPERS | _LAX_CONSUMERS):
                continue
            for arg in list(call.args) + [k.value for k in call.keywords]:
                if isinstance(arg, ast.Lambda):
                    roots.add(arg)
                elif isinstance(arg, ast.Name) and arg.id in by_name:
                    roots.update(by_name[arg.id])

        roots = {fn for fn in roots if not self._is_host_marked(fn)}

        # 4. fixpoint: nested defs + module functions called from a context
        contexts = set(roots)
        changed = True
        while changed:
            changed = False
            for fn in self.functions:
                if fn in contexts or self._is_host_marked(fn):
                    continue
                enc = self.enclosing_function(fn)
                if enc is not None and enc in contexts:
                    contexts.add(fn)
                    changed = True
            for fn in list(contexts):
                for call in ast.walk(fn):
                    if isinstance(call, ast.Call) and isinstance(
                        call.func, ast.Name
                    ):
                        callee = module_level.get(call.func.id)
                        if (
                            callee is not None
                            and callee not in contexts
                            and not self._is_host_marked(callee)
                        ):
                            contexts.add(callee)
                            changed = True
        return contexts

    # -- traced-name taint ----------------------------------------------

    def traced_names(self, fn: ast.AST) -> Set[str]:
        """Names in ``fn`` that (approximately) hold traced values:
        parameters plus jnp/lax-derived assignments, to a fixpoint."""
        cached = self._taint_cache.get(fn)
        if cached is not None:
            return cached
        taint: Set[str] = set()
        args = fn.args
        for a in (
            list(args.posonlyargs)
            + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            if a.arg not in ("self", "cls"):
                taint.add(a.arg)

        def rhs_tainted(expr: ast.AST) -> bool:
            if _names_in(expr) & taint:
                return True
            for sub in ast.walk(expr):
                if isinstance(sub, ast.Call):
                    chain = _attr_chain(sub.func) or ""
                    root = chain.split(".", 1)[0]
                    if root in ("jnp", "lax", "jax"):
                        return True
            return False

        def bind_targets(target: ast.AST) -> Iterator[str]:
            for sub in ast.walk(target):
                if isinstance(sub, ast.Name):
                    yield sub.id

        changed = True
        while changed:
            changed = False
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign) and rhs_tainted(node.value):
                    for t in node.targets:
                        for name in bind_targets(t):
                            if name not in taint:
                                taint.add(name)
                                changed = True
                elif isinstance(node, ast.AugAssign) and rhs_tainted(
                    node.value
                ):
                    for name in bind_targets(node.target):
                        if name not in taint:
                            taint.add(name)
                            changed = True
                elif isinstance(node, ast.For) and rhs_tainted(node.iter):
                    for name in bind_targets(node.target):
                        if name not in taint:
                            taint.add(name)
                            changed = True
        self._taint_cache[fn] = taint
        return taint

    def scope_taint(self, fn: ast.AST) -> Set[str]:
        """Traced names visible in ``fn`` including closure captures from
        enclosing functions (conservatively unioned)."""
        taint = set(self.traced_names(fn))
        enc = self.enclosing_function(fn)
        while enc is not None:
            taint |= self.traced_names(enc)
            enc = self.enclosing_function(enc)
        return taint

    def src(self, node: ast.AST) -> str:
        line = getattr(node, "lineno", 0)
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


# ---------------------------------------------------------------------------
# rule framework


class Rule:
    name: str = ""
    summary: str = ""
    scope: str = "ringpop_tpu/"  # human-readable scope note

    def applies(self, mod: ModuleInfo) -> bool:
        return True

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, mod: ModuleInfo, node: ast.AST, message: str) -> Finding:
        return Finding(
            rule=self.name,
            path=mod.rel,
            line=getattr(node, "lineno", 0),
            message=message,
            prong="ast",
            source=mod.src(node),
            end_line=getattr(node, "end_lineno", 0) or 0,
        )


def _in_device_paths(mod: ModuleInfo, paths: Tuple[str, ...]) -> bool:
    rel = mod.rel.split("ringpop_tpu/", 1)[-1]
    return rel.startswith(paths)


class HostCoerceRule(Rule):
    name = "host-coerce"
    summary = (
        "float()/int()/bool()/complex()/.item() on a traced value inside a "
        "jit context forces a host sync (or raises at trace time)"
    )
    scope = "jit contexts"

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for fn in mod.functions:
            if fn not in mod.jit_contexts:
                continue
            taint = mod.scope_taint(fn)
            for node in _own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                if isinstance(node.func, ast.Name) and node.func.id in _COERCERS:
                    if node.args and _names_in(node.args[0]) & taint:
                        yield self.finding(
                            mod,
                            node,
                            f"{node.func.id}() applied to traced value "
                            f"{sorted(_names_in(node.args[0]) & taint)} — "
                            "use jnp dtype ops or hoist to the host side",
                        )
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item"
                    and _names_in(node.func.value) & taint
                ):
                    yield self.finding(
                        mod,
                        node,
                        ".item() on traced value forces device->host sync",
                    )


class NpOnTracedRule(Rule):
    name = "np-on-traced"
    summary = (
        "np.asarray/np.prod/np.sum/... on a traced value silently pulls the "
        "array to host inside a jit context"
    )
    scope = "jit contexts"

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for fn in mod.functions:
            if fn not in mod.jit_contexts:
                continue
            taint = mod.scope_taint(fn)
            for node in _own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func) or ""
                parts = chain.split(".")
                if (
                    len(parts) == 2
                    and parts[0] in ("np", "numpy")
                    and parts[1] in _NP_HOST_FUNCS
                ):
                    hit = set()
                    for arg in node.args:
                        hit |= _names_in(arg) & taint
                    if hit:
                        yield self.finding(
                            mod,
                            node,
                            f"{chain}() on traced value {sorted(hit)} — use "
                            f"the jnp twin (or math.* for static shapes)",
                        )


class ImplicitDtypeRule(Rule):
    name = "implicit-dtype"
    summary = (
        "array constructor without an explicit dtype: the default depends "
        "on the x64 flag and breaks uint32/int32 discipline"
    )
    scope = "ops/, models/sim/"

    def applies(self, mod: ModuleInfo) -> bool:
        return _in_device_paths(mod, DTYPE_PATHS)

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func) or ""
            parts = chain.split(".")
            if len(parts) != 2 or parts[0] != "jnp":
                continue
            ctor = parts[1]
            if ctor not in _DTYPE_CTORS:
                continue
            if any(k.arg == "dtype" for k in node.keywords):
                continue
            if len(node.args) > _DTYPE_POS[ctor]:
                continue  # positional dtype
            yield self.finding(
                mod,
                node,
                f"jnp.{ctor}(...) without explicit dtype",
            )


class ImplicitAccumDtypeRule(Rule):
    name = "implicit-accum-dtype"
    summary = (
        "accumulating reduction without a reviewable accumulator dtype: "
        "pass dtype= or pin the operand with .astype(...) — jnp.sum "
        "upcasts with the x64 flag, and int32 accumulators are what the "
        "overflow prong prices at declared scale"
    )
    scope = "ops/, models/sim/"

    _ACCUM = ("sum", "cumsum", "prod", "cumprod")
    # calls whose first operand is one of these are visibly pinned: the
    # value range a reviewer (and the interval certifier) must check is
    # stated inline even though jnp.sum still widens the accumulator
    # under x64 — THAT half is dtype-overflow's job, not the lint's
    _PINNERS = ("astype", "view")

    def applies(self, mod: ModuleInfo) -> bool:
        return _in_device_paths(mod, DTYPE_PATHS)

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func) or ""
            parts = chain.split(".")
            if len(parts) != 2 or parts[0] != "jnp":
                continue
            if parts[1] not in self._ACCUM:
                continue
            if any(k.arg == "dtype" for k in node.keywords):
                continue
            op = node.args[0] if node.args else None
            if (
                isinstance(op, ast.Call)
                and isinstance(op.func, ast.Attribute)
                and op.func.attr in self._PINNERS
            ):
                continue
            yield self.finding(
                mod,
                node,
                f"jnp.{parts[1]}(...) without explicit accumulator dtype "
                "(dtype= kwarg or .astype-pinned operand)",
            )


class PyRandomTimeRule(Rule):
    name = "py-random-time"
    summary = (
        "random/time/np.random calls inside a jit context bake trace-time "
        "nondeterminism into the compiled program"
    )
    scope = "jit contexts"

    _MODULES = ("random", "time", "datetime", "numpy.random")

    def _from_imports(self, mod: ModuleInfo) -> Dict[str, str]:
        """local alias -> fully qualified origin, for both
        `from X import Y [as Z]` and `import X as Z` over the
        nondeterminism-bearing modules."""
        out: Dict[str, str] = {}
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.ImportFrom):
                if node.module in self._MODULES:
                    for alias in node.names:
                        out[alias.asname or alias.name] = (
                            f"{node.module}.{alias.name}"
                        )
            elif isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name in self._MODULES and alias.asname:
                        out[alias.asname] = alias.name
        return out

    @staticmethod
    def _nondeterministic(chain: str) -> bool:
        leaf = chain.rsplit(".", 1)[-1]
        if chain.startswith(("random.", "time.", "np.random.", "numpy.random.")):
            return True
        # datetime is mostly deterministic constructors; only the clock
        # reads are trace-time hazards
        return chain.startswith("datetime.") and leaf in (
            "now",
            "utcnow",
            "today",
        )

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        aliases = self._from_imports(mod)
        for fn in mod.functions:
            if fn not in mod.jit_contexts:
                continue
            for node in _own_nodes(fn):
                if not isinstance(node, ast.Call):
                    continue
                chain = _attr_chain(node.func) or ""
                if not chain:
                    continue
                # resolve `from time import time`-style local names back
                # to their origin module before testing
                head, _, rest = chain.partition(".")
                if head in aliases:
                    chain = aliases[head] + (f".{rest}" if rest else "")
                if self._nondeterministic(chain):
                    yield self.finding(
                        mod,
                        node,
                        f"{chain}() inside a jit context is evaluated once "
                        "at trace time — thread rng state / stamps instead",
                    )


class MutableDefaultRule(Rule):
    name = "mutable-default"
    summary = (
        "mutable or array-valued default argument: one instance is shared "
        "across calls (and an array default pins a device buffer at import)"
    )
    scope = "ringpop_tpu/"

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for fn in mod.functions:
            args = fn.args
            for default in list(args.defaults) + [
                d for d in args.kw_defaults if d is not None
            ]:
                bad = None
                if isinstance(default, (ast.List, ast.Dict, ast.Set)):
                    bad = "mutable literal"
                elif isinstance(default, ast.Call):
                    chain = _attr_chain(default.func) or ""
                    root = chain.split(".", 1)[0]
                    if root in ("np", "numpy", "jnp", "jax"):
                        bad = f"array constructor {chain}()"
                    elif chain in ("list", "dict", "set", "bytearray"):
                        bad = f"{chain}()"
                if bad:
                    yield self.finding(
                        mod,
                        default,
                        f"default argument is a {bad} — use None + "
                        "in-function construction",
                    )


class BlockUntilReadyRule(Rule):
    name = "block-until-ready"
    summary = (
        "block_until_ready in library code serializes the dispatch "
        "pipeline; only bench/obs code may sync"
    )
    scope = "ringpop_tpu/ except obs/"

    def applies(self, mod: ModuleInfo) -> bool:
        rel = mod.rel.split("ringpop_tpu/", 1)[-1]
        return not rel.startswith(SYNC_OK_PATHS)

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if (
                isinstance(node, ast.Attribute)
                and node.attr == "block_until_ready"
            ):
                yield self.finding(
                    mod,
                    node,
                    "block_until_ready outside bench/obs",
                )


class CallbackInDeviceRule(Rule):
    name = "callback-in-device"
    summary = (
        "host callback primitives in device modules break the "
        "gate-equivalence-safe scanned tick (and multi-chip SPMD)"
    )
    scope = "ops/, models/sim/, models/ring/, parallel/"

    def applies(self, mod: ModuleInfo) -> bool:
        return _in_device_paths(mod, DEVICE_PATHS)

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = _attr_chain(node.func) or ""
            leaf = chain.rsplit(".", 1)[-1]
            if leaf in _CALLBACK_NAMES or chain in (
                "jax.debug.print",
                "jax.debug.callback",
                "debug.print",
                "debug.callback",
            ):
                yield self.finding(
                    mod,
                    node,
                    f"host callback {chain or leaf}() in a device module",
                )


class StaleRefAcrossDonationRule(Rule):
    name = "stale-ref-across-donation"
    summary = (
        "a bare device-state binding held live across a donating dispatch "
        "reads donated buffers (the PR-7/PR-8 aliasing hazard) — snapshot "
        "via device_get/host_copy_states before dispatching"
    )
    scope = "models/sim/, models/route/, parallel/, fuzz/"

    # carry attributes whose buffers a donating dispatch invalidates
    _STATE_ATTRS = {"state", "rstate"}

    def applies(self, mod: ModuleInfo) -> bool:
        return _in_device_paths(mod, DEVICE_PATHS + ("fuzz/",))

    # -- module-level: which factories build donating jits ----------------

    @staticmethod
    def _donating_factories(mod: ModuleInfo) -> Set[str]:
        """Module-level functions whose body jits with ``donate_argnums``
        (storm._tick_fn / plane._routed_fns / mesh._storm_tick_fn), plus
        names bound directly to such a jit."""
        out: Set[str] = set()
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Call) and any(
                        k.arg == "donate_argnums" for k in sub.keywords
                    ):
                        out.add(node.name)
                        break
            elif isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if any(
                    k.arg == "donate_argnums"
                    for k in node.value.keywords
                ):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            out.add(t.id)
        return out

    @staticmethod
    def _donating_attrs(cls: ast.ClassDef, factories: Set[str]) -> Set[str]:
        """``self.X`` attributes a class binds from a donating factory
        (``self._tick = _tick_fn(...)``; tuple unpacking included:
        ``self._tick, self._scanned = _routed_fns(...)``)."""
        out: Set[str] = set()
        for node in ast.walk(cls):
            if not isinstance(node, ast.Assign):
                continue
            call = node.value
            if not (
                isinstance(call, ast.Call)
                and isinstance(call.func, ast.Name)
                and call.func.id in factories
            ):
                continue
            for t in node.targets:
                targets = t.elts if isinstance(t, ast.Tuple) else [t]
                for el in targets:
                    if (
                        isinstance(el, ast.Attribute)
                        and isinstance(el.value, ast.Name)
                        and el.value.id == "self"
                    ):
                        out.add(el.attr)
        return out

    # -- per-method linear scan -------------------------------------------

    def _check_method(
        self, mod: ModuleInfo, fn: ast.AST, donating: Set[str]
    ) -> Iterator[Finding]:
        # pass 1 — bare snapshots: `alias = <chain>.state` with NO
        # wrapping call (a call — device_get, host_copy_states,
        # np.asarray, ... — breaks the zero-copy aliasing and is the
        # sanctioned idiom).  _own_nodes walks in tree order, not line
        # order, so snapshot/dispatch pairing is by line comparison.
        snapshots: Dict[str, Tuple[int, str]] = {}  # name -> (line, chain)
        for node in _own_nodes(fn):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Attribute
            ):
                chain = _attr_chain(node.value)
                if chain and chain.rsplit(".", 1)[-1] in self._STATE_ATTRS:
                    for t in node.targets:
                        # FIRST binding wins (walk order is tree order,
                        # not line order): a later re-snapshot must not
                        # hide that the name was stale at the dispatch —
                        # post-dispatch rebinds are handled by the
                        # rebinds list below
                        if isinstance(t, ast.Name) and (
                            t.id not in snapshots
                            or node.lineno < snapshots[t.id][0]
                        ):
                            snapshots[t.id] = (node.lineno, chain)
        if not snapshots:
            return
        # pass 2 — donating dispatches and the snapshot names whose
        # buffers each one invalidates
        dispatches: List[Tuple[int, Set[str]]] = []  # (end line, dead names)
        for node in _own_nodes(fn):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "self"
                and node.func.attr in donating
            ):
                continue
            call_line = node.lineno
            dead: Set[str] = set()
            arg_chains: Set[str] = set()
            for arg in node.args:
                for sub in ast.walk(arg):
                    if isinstance(sub, ast.Name):
                        if (
                            sub.id in snapshots
                            and snapshots[sub.id][0] < call_line
                        ):
                            dead.add(sub.id)
                    elif isinstance(sub, ast.Attribute):
                        chain = _attr_chain(sub)
                        if chain:
                            arg_chains.add(chain)
            # a snapshot whose source chain is itself dispatched
            # (`pre = self.state` ... `self._tick(self.state, ...)`)
            # aliases the same donated buffers
            for name, (line, chain) in snapshots.items():
                if chain in arg_chains and line < call_line:
                    dead.add(name)
            if dead:
                dispatches.append(
                    (getattr(node, "end_lineno", node.lineno), dead)
                )
        if not dispatches:
            return
        # a Load strictly after the dispatch with no intervening rebind
        # is the stale read.  Line order approximates execution order —
        # a read textually before the dispatch inside a loop is a
        # (documented) false negative, never a false positive.
        rebinds: List[Tuple[int, str]] = []
        for node in _own_nodes(fn):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id in snapshots:
                        rebinds.append((node.lineno, t.id))
        reported: Set[str] = set()
        for node in _own_nodes(fn):
            if not (
                isinstance(node, ast.Name)
                and isinstance(node.ctx, ast.Load)
                and node.id not in reported
            ):
                continue
            for line, dead in dispatches:
                if node.id in dead and node.lineno > line and not any(
                    name == node.id and line < rb < node.lineno
                    for rb, name in rebinds
                ):
                    reported.add(node.id)
                    yield self.finding(
                        mod,
                        node,
                        (
                            f"'{node.id}' aliases device state donated "
                            f"to a dispatch at line {line} — its "
                            "buffers are dead; host-copy first "
                            "(device_get / host_copy_states) or "
                            "re-snapshot after the dispatch"
                        ),
                    )
                    break

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        factories = self._donating_factories(mod)
        if not factories:
            return
        for cls in ast.walk(mod.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            donating = self._donating_attrs(cls, factories)
            if not donating:
                continue
            for node in cls.body:
                if isinstance(
                    node, (ast.FunctionDef, ast.AsyncFunctionDef)
                ):
                    yield from self._check_method(mod, node, donating)


class AssertOnTracedRule(Rule):
    name = "assert-on-traced"
    summary = (
        "assert over a traced value inside a jit context either raises at "
        "trace time or silently checks nothing per step"
    )
    scope = "jit contexts"

    def check(self, mod: ModuleInfo) -> Iterator[Finding]:
        for fn in mod.functions:
            if fn not in mod.jit_contexts:
                continue
            taint = mod.scope_taint(fn)
            for node in _own_nodes(fn):
                if isinstance(node, ast.Assert):
                    hit = _names_in(node.test) & taint
                    if hit:
                        yield self.finding(
                            mod,
                            node,
                            f"assert over traced value {sorted(hit)} — use "
                            "checkify or a host-side validation path",
                        )


ALL_RULES: List[Rule] = [
    HostCoerceRule(),
    NpOnTracedRule(),
    ImplicitDtypeRule(),
    ImplicitAccumDtypeRule(),
    PyRandomTimeRule(),
    MutableDefaultRule(),
    BlockUntilReadyRule(),
    CallbackInDeviceRule(),
    StaleRefAcrossDonationRule(),
    AssertOnTracedRule(),
]

RULES_BY_NAME: Dict[str, Rule] = {r.name: r for r in ALL_RULES}


def lint_source(
    source: str,
    rel: str,
    path: Optional[Path] = None,
    rules: Optional[Iterable[Rule]] = None,
    respect_suppressions: bool = True,
) -> List[Finding]:
    """Lint one module's source; returns unsuppressed findings."""
    mod = ModuleInfo(path or Path(rel), rel, source)
    out: List[Finding] = []
    for rule in ALL_RULES if rules is None else rules:
        if not rule.applies(mod):
            continue
        for f in rule.check(mod):
            if respect_suppressions and fmod.is_suppressed(
                f, mod.suppressions
            ):
                continue
            out.append(f)
    return out


def lint_paths(
    root: Path,
    files: Optional[Iterable[Path]] = None,
    rules: Optional[Iterable[Rule]] = None,
) -> List[Finding]:
    """Lint every ``*.py`` under ``root`` (or just ``files``); paths in
    findings are relative to ``root``'s parent."""
    base = root.parent
    targets = (
        sorted(p for p in root.rglob("*.py") if "__pycache__" not in p.parts)
        if files is None
        else [Path(f) for f in files]
    )
    explicit = files is not None
    out: List[Finding] = []
    for path in targets:
        try:
            rel = path.resolve().relative_to(base.resolve()).as_posix()
        except ValueError:
            rel = path.as_posix()
        try:
            source = path.read_text()
        except (OSError, UnicodeDecodeError) as e:
            if explicit:
                # a typo'd CI/pre-commit target must not read as "clean"
                out.append(
                    Finding(
                        rule="unreadable-file",
                        path=rel,
                        line=0,
                        message=f"could not read explicit lint target: {e}",
                        prong="ast",
                    )
                )
            continue
        try:
            out.extend(lint_source(source, rel, path=path, rules=rules))
        except SyntaxError as e:
            out.append(
                Finding(
                    rule="syntax-error",
                    path=rel,
                    line=e.lineno or 0,
                    message=f"could not parse: {e.msg}",
                    prong="ast",
                )
            )
    return out
