"""Interval-range abstract interpretation over ClosedJaxprs (ISSUE 18).

The numeric half of the scale certifier: propagate per-variable VALUE
RANGES from declared input contracts through a traced entry point's
jaxpr — riding :mod:`ringpop_tpu.analysis.dataflow`'s recursive walker
(``pjit`` / ``scan`` / ``while`` / ``cond`` / ``shard_map`` /
``pallas_call``) in precise mode, with scan/while carries run to a
widening fixpoint — and report every equation whose result interval can
escape its dtype's representable range.

Domain
------
A value is ``None`` (unknown — floats, pallas outputs, unmodeled
primitives) or an :class:`Interval` of Python ints with ``None``
endpoints meaning ±∞.  Constvars and literals seed exact ranges from
their concrete values; entry inputs seed from the declared contracts
(:func:`input_contract`): ticks/stamps ∈ [-2, 2^20] for signed lanes
(the ``-1``/``-2`` sentinels plus ROADMAP item 1's serving envelope),
full range for unsigned lanes (mod-2^32 wrap is the repo's hash
contract, never a finding), [0, 1] for bools.

Termination: the carry-feedback join is a *widening with thresholds* —
a bound that grows between loop iterations jumps to the next landmark
(0, ±1, the tick ceiling, the int32/uint32/int64 edges, then ±∞), so a
``min``/``clamp``-stabilized counter converges to a finite range while
a bare ``c + 1`` carry provably escapes in a handful of iterations.

Events (:class:`RangeEvent`) carry a stable ``key`` so the consumer
prong (:mod:`ringpop_tpu.analysis.overflow`) can hold an explicit,
justified allowlist:

- ``dtype-overflow`` — a signed-integer result interval escapes its
  dtype (per-equation, including lossy ``convert_element_type``
  narrowing; same-width int<->int reinterprets are the sanctioned
  bit-cast idiom and stay silent).  ``reduce_sum`` is additionally
  checked at the entry's DECLARED scale: an accumulator fine at the
  n=8 trace can still wrap when the reduced axis is an N axis.
- ``unbounded-carry`` — a scan/while integer carry widened past its
  dtype: a per-tick-growing counter, invisible to any fixed-length
  trace, wraps under a long enough run.
- ``index-overflow`` — shape-derived index-space safety at the
  declared N ceiling: an ``iota`` / ``gather`` / ``scatter`` /
  ``dynamic_slice`` index lane whose EXTENT at scale exceeds the index
  dtype, even though the toy trace is fine.

Scale model
-----------
:class:`ScaleSpec` declares, per entry point, how trace-time toy dims
extrapolate: dims equal to ``c * toy_n`` (``c`` from a small declared
coefficient set) scale to ``c * n_max``; ``dim_map`` pins named toy
dims to their envelope values (the rumor-table capacity ``u`` and its
word width are bounded by design — ``ScalableParams.u`` — and must NOT
ride the N axis).  The same spec prices abstract buffer footprints for
the memory-feasibility pass (:mod:`ringpop_tpu.analysis.scale_budget`).
"""

from __future__ import annotations

import dataclasses
import fnmatch
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ringpop_tpu.analysis import dataflow

__all__ = [
    "Interval",
    "RangeEvent",
    "ScaleSpec",
    "RangeVisitor",
    "analyze_jaxpr",
    "input_contract",
    "entry_scale",
    "scaled_dim",
    "TICK_CEILING",
    "N_MAX_PODS",
    "ENTRY_SCALES",
]

# ---------------------------------------------------------------------------
# declared contracts (ISSUE 18): the envelopes the certifier proves against

TICK_CEILING = 1 << 20  # ROADMAP item 1: long-running serving, ~2.4 days
N_MAX_PODS = 64 << 20  # ROADMAP item 3: 64Mi-node pod-scale ceiling
FULL_N_MAX = 1 << 16  # full-fidelity [N,N]-plane engine ceiling
ROUTE_N_MAX = 16 << 20  # routing plane ceiling
HASH_ROWS_MAX = 1 << 20  # checksum/farmhash row-batch ceiling
U_ENVELOPE = 512  # ScalableParams.u default: rumor table capacity
SENTINEL_LO = -2  # the -1/-2 "never"/"tombstone" stamp sentinels


@dataclasses.dataclass(frozen=True)
class Interval:
    """Closed integer interval; a ``None`` endpoint is ±∞."""

    lo: Optional[int]
    hi: Optional[int]

    def __repr__(self) -> str:  # compact in findings text
        lo = "-inf" if self.lo is None else str(self.lo)
        hi = "+inf" if self.hi is None else str(self.hi)
        return f"[{lo}, {hi}]"


def point(v: int) -> Interval:
    return Interval(int(v), int(v))


FULL = Interval(None, None)
BOOL = Interval(0, 1)


def _min(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None or b is None:
        return None
    return min(a, b)


def _max(a: Optional[int], b: Optional[int]) -> Optional[int]:
    if a is None or b is None:
        return None
    return max(a, b)


def union(a: Optional[Interval], b: Optional[Interval]) -> Optional[Interval]:
    """Precise union (no widening) — top absorbs."""
    if a is None or b is None:
        return None
    return Interval(_min(a.lo, b.lo), _max(a.hi, b.hi))


def intersect_hull(a: Interval, b: Interval) -> Interval:
    """Intersection, falling back to ``a`` clamped into ``b``'s hull
    (used for dtype clamping where emptiness cannot arise)."""
    lo = a.lo if b.lo is None else (b.lo if a.lo is None else max(a.lo, b.lo))
    hi = a.hi if b.hi is None else (b.hi if a.hi is None else min(a.hi, b.hi))
    if lo is not None and hi is not None and lo > hi:
        return b
    return Interval(lo, hi)


# widening thresholds: a carry bound that grows between loop iterations
# jumps outward to the next landmark instead of inching forever
_HI_LANDMARKS: Tuple[Optional[int], ...] = (
    0,
    1,
    (1 << 8) - 1,
    (1 << 16) - 1,
    TICK_CEILING,
    (1 << 31) - 1,
    (1 << 32) - 1,
    (1 << 63) - 1,
    None,
)
_LO_LANDMARKS: Tuple[Optional[int], ...] = (
    0,
    SENTINEL_LO,
    -TICK_CEILING,
    -(1 << 31),
    -(1 << 63),
    None,
)


def _widen_hi(v: Optional[int]) -> Optional[int]:
    if v is None:
        return None
    for lm in _HI_LANDMARKS:
        if lm is None or v <= lm:
            return lm
    return None


def _widen_lo(v: Optional[int]) -> Optional[int]:
    if v is None:
        return None
    for lm in _LO_LANDMARKS:
        if lm is None or v >= lm:
            return lm
    return None


def widen(old: Optional[Interval], new: Optional[Interval]) -> Optional[Interval]:
    """``old ∇ (old ∪ new)``: keep stable bounds, jump grown ones to
    the next landmark.  Guarantees fixpoint in O(#landmarks) rounds."""
    if old is None or new is None:
        return None
    u = union(old, new)
    lo = u.lo if (old.lo is not None and u.lo == old.lo) else _widen_lo(u.lo)
    if old.lo is None:
        lo = None
    hi = u.hi if (old.hi is not None and u.hi == old.hi) else _widen_hi(u.hi)
    if old.hi is None:
        hi = None
    return Interval(lo, hi)


# ---------------------------------------------------------------------------
# dtype lattice anchors


def _np_dtype(dt):
    try:
        return np.dtype(dt)
    except TypeError:
        return None


def dtype_interval(dt) -> Optional[Interval]:
    """Representable range for an integer/bool dtype; None for floats
    and anything else (unranged)."""
    dt = _np_dtype(dt)
    if dt is None:
        return None
    if dt == np.dtype(bool):
        return BOOL
    if dt.kind in ("i", "u"):
        info = np.iinfo(dt)
        return Interval(int(info.min), int(info.max))
    return None


def _is_signed(dt) -> bool:
    dt = _np_dtype(dt)
    return dt is not None and dt.kind == "i"


def _is_int_like(dt) -> bool:
    dt = _np_dtype(dt)
    return dt is not None and (dt.kind in ("i", "u") or dt == np.dtype(bool))


def input_contract(aval) -> Optional[Interval]:
    """Declared contract for one entry-point input leaf, by dtype.

    Unsigned lanes are the hash/bitmask planes: full range, wrap is the
    contract.  Signed lanes are tick stamps, indices and counts: the
    ``-1``/``-2`` sentinels up to the serving-envelope tick ceiling —
    NOT the full int32 range, or every add would (vacuously) overflow.
    """
    dt = getattr(aval, "dtype", None)
    if dt is None:
        return None
    dt = _np_dtype(dt)
    if dt is None:
        return None
    if dt == np.dtype(bool):
        return BOOL
    if dt.kind == "u":
        return dtype_interval(dt)
    if dt.kind == "i":
        return Interval(SENTINEL_LO, TICK_CEILING)
    return None


# ---------------------------------------------------------------------------
# declared per-entry scale model


@dataclasses.dataclass(frozen=True)
class ScaleSpec:
    """How one entry point's trace-time toy dims extrapolate to scale.

    ``toy_n`` is the member-count axis at trace time (the registry
    traces everything at n=8); a dim equal to ``c * toy_n`` for ``c``
    in ``coeffs`` scales to ``c * n_max``.  ``dim_map`` pins specific
    toy dims to their declared envelope (capacity knobs like the rumor
    table that must NOT ride the N axis); it wins over the coefficient
    rule.  Dims matching neither are trace-time constants.
    """

    toy_n: int = 8
    n_max: int = N_MAX_PODS
    coeffs: Tuple[int, ...] = (1,)
    dim_map: Tuple[Tuple[int, int], ...] = ()

    def label(self) -> str:
        return f"toy_n={self.toy_n} n_max={self.n_max}"


def _dim_rule(d: int, spec: ScaleSpec) -> Tuple[str, int]:
    """Classify one trace-time dim: ``("pinned", env)`` for a dim_map
    capacity envelope (constant at scale), ``("scaled", c)`` for a
    ``c*toy_n`` dim riding the N axis, ``("const", d)`` otherwise."""
    for toy, env in spec.dim_map:
        if d == toy:
            return "pinned", env
    for c in spec.coeffs:
        if c > 0 and d == c * spec.toy_n:
            return "scaled", c
    return "const", d


def scaled_dim(d: int, spec: ScaleSpec) -> int:
    """The declared at-scale extent of one trace-time dim."""
    kind, v = _dim_rule(d, spec)
    if kind == "pinned":
        return v
    if kind == "scaled":
        return v * spec.n_max
    return d


# u=128 / w=4 trace shapes scale to the ScalableParams.u capacity
# envelope, not with N (rumor-table capacity is bounded by design);
# 32 is the uint32 bit-lane axis the exchange unpacks into — a word
# width, never a scaled dim
_SCALABLE_DIMS = ((128, U_ENVELOPE), (4, U_ENVELOPE // 32), (32, 32))

# first fnmatch wins; the trailing "*" is the conservative default
ENTRY_SCALES: Tuple[Tuple[str, ScaleSpec], ...] = (
    # full-fidelity engine: [N,N] planes — ceiling is the ROADMAP
    # item-2 full-engine ladder, not the pod-scale axis
    ("engine-tick-scan*", ScaleSpec(8, FULL_N_MAX)),
    ("fused-apply-*", ScaleSpec(8, FULL_N_MAX)),
    ("fused-piggyback-*", ScaleSpec(8, FULL_N_MAX)),
    ("fuzz-scenario-scan-full", ScaleSpec(8, FULL_N_MAX)),
    ("checkpoint-restore*", ScaleSpec(8, FULL_N_MAX)),
    # scalable O(N·U) engine + exchange: N rides to the pod ceiling,
    # u/w stay at the capacity envelope
    ("engine-scalable-*", ScaleSpec(8, N_MAX_PODS, dim_map=_SCALABLE_DIMS)),
    (
        "fuzz-scenario-scan-scalable",
        ScaleSpec(8, N_MAX_PODS, dim_map=_SCALABLE_DIMS),
    ),
    ("exchange-*", ScaleSpec(8, N_MAX_PODS, dim_map=_SCALABLE_DIMS)),
    # row-batched hash pipelines: rows scale, digest width is constant
    ("fused-checksum-*", ScaleSpec(8, HASH_ROWS_MAX)),
    ("farmhash-*", ScaleSpec(8, HASH_ROWS_MAX)),
    # consistent-hash ring + routing plane: N members x 100 replica
    # points — the flat ring dim (toy 800 = 100*8) rides the N axis
    # with coefficient 100.  Declared ceiling is the routing plane's
    # 16Mi, NOT the 64Mi pod axis: the certifier proved the int32
    # dynamic_slice index lane caps the flat ring at
    # floor(int32_max / 100) ~ 21.4M members — 64Mi needs an int64
    # ring index (ROADMAP item 3 follow-up), 16Mi (1.6e9 points) fits
    ("ring-device-lookup", ScaleSpec(8, ROUTE_N_MAX, coeffs=(1, 100))),
    ("route-*", ScaleSpec(8, ROUTE_N_MAX, coeffs=(1, 100))),
    ("*", ScaleSpec(8, N_MAX_PODS)),
)


def entry_scale(name: str) -> ScaleSpec:
    for pat, spec in ENTRY_SCALES:
        if fnmatch.fnmatchcase(name, pat):
            return spec
    return ScaleSpec()


# ---------------------------------------------------------------------------
# interval arithmetic (None endpoint = ±∞, None interval = top)


def iv_neg(a: Optional[Interval]) -> Optional[Interval]:
    if a is None:
        return None
    return Interval(
        None if a.hi is None else -a.hi, None if a.lo is None else -a.lo
    )


def iv_add(a: Optional[Interval], b: Optional[Interval]) -> Optional[Interval]:
    if a is None or b is None:
        return None
    lo = None if (a.lo is None or b.lo is None) else a.lo + b.lo
    hi = None if (a.hi is None or b.hi is None) else a.hi + b.hi
    return Interval(lo, hi)


def iv_sub(a: Optional[Interval], b: Optional[Interval]) -> Optional[Interval]:
    return iv_add(a, iv_neg(b))


def iv_mul(a: Optional[Interval], b: Optional[Interval]) -> Optional[Interval]:
    if a is None or b is None:
        return None
    if None not in (a.lo, a.hi, b.lo, b.hi):
        c = (a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi)
        return Interval(min(c), max(c))
    # both known nonnegative: the product's lower bound survives
    if a.lo is not None and b.lo is not None and a.lo >= 0 and b.lo >= 0:
        hi = None if (a.hi is None or b.hi is None) else a.hi * b.hi
        return Interval(a.lo * b.lo, hi)
    return FULL


def iv_scale(a: Optional[Interval], k: int) -> Optional[Interval]:
    return iv_mul(a, point(k))


def iv_min(a: Optional[Interval], b: Optional[Interval]) -> Optional[Interval]:
    if a is None or b is None:
        return None
    return Interval(_min(a.lo, b.lo), _min(a.hi, b.hi))


def iv_max(a: Optional[Interval], b: Optional[Interval]) -> Optional[Interval]:
    if a is None or b is None:
        return None
    return Interval(_max(a.lo, b.lo), _max(a.hi, b.hi))


def iv_abs(a: Optional[Interval]) -> Optional[Interval]:
    if a is None:
        return None
    if a.lo is not None and a.lo >= 0:
        return a
    if a.hi is not None and a.hi <= 0:
        return iv_neg(a)
    hi = None
    if a.lo is not None and a.hi is not None:
        hi = max(-a.lo, a.hi)
    return Interval(0, hi)


def iv_div(a: Optional[Interval], b: Optional[Interval]) -> Optional[Interval]:
    """Integer division, conservative: only when the divisor interval
    is finite and excludes 0."""
    if a is None or b is None or None in (a.lo, a.hi, b.lo, b.hi):
        return None
    if b.lo <= 0 <= b.hi:
        return None
    c = []
    for x in (a.lo, a.hi):
        for y in (b.lo, b.hi):
            c.append(int(x / y) if (x < 0) != (y < 0) else x // y)
    return Interval(min(c), max(c))


def iv_rem(a: Optional[Interval], b: Optional[Interval]) -> Optional[Interval]:
    """lax.rem (C-style, sign of the dividend)."""
    if b is None or b.lo is None or b.hi is None:
        return None
    m = max(abs(b.lo), abs(b.hi))
    if m == 0:
        return None
    lo = 0
    if a is None or a.lo is None or a.lo < 0:
        lo = -(m - 1)
    hi = m - 1
    if a is not None and a.lo is not None and a.hi is not None:
        if 0 <= a.hi < m and a.lo >= 0:
            return a  # fits entirely below the modulus
    return Interval(lo, hi)


def _bit_ceiling(v: int) -> int:
    """Smallest 2^k - 1 >= v (for or/xor upper bounds)."""
    return (1 << max(v, 0).bit_length()) - 1


def iv_and(a: Optional[Interval], b: Optional[Interval]) -> Optional[Interval]:
    if a is None or b is None:
        return None
    if a.lo is not None and b.lo is not None and a.lo >= 0 and b.lo >= 0:
        return Interval(0, _min(a.hi, b.hi))
    return None


def iv_orxor(a: Optional[Interval], b: Optional[Interval]) -> Optional[Interval]:
    if a is None or b is None:
        return None
    if (
        a.lo is not None
        and b.lo is not None
        and a.lo >= 0
        and b.lo >= 0
        and a.hi is not None
        and b.hi is not None
    ):
        return Interval(0, _bit_ceiling(max(a.hi, b.hi)))
    return None


def iv_shl(a: Optional[Interval], b: Optional[Interval]) -> Optional[Interval]:
    if (
        a is None
        or b is None
        or None in (a.lo, a.hi, b.lo, b.hi)
        or a.lo < 0
        or b.lo < 0
        or b.hi > 64
    ):
        return None
    return Interval(a.lo << b.lo, a.hi << b.hi)


def iv_shr(a: Optional[Interval], b: Optional[Interval]) -> Optional[Interval]:
    if a is None or b is None or a.lo is None or a.lo < 0:
        return None  # logical shift of a negative reinterprets the sign bit
    if b is None or b.lo is None or b.lo < 0:
        return Interval(0, a.hi)
    hi = None if a.hi is None else a.hi >> b.lo
    lo = 0
    if a.lo is not None and b.hi is not None:
        lo = a.lo >> b.hi
    return Interval(lo, hi)


# ---------------------------------------------------------------------------
# events


def _eqn_src(eqn) -> str:
    """Best-effort ``file.py:line (fn)`` for an equation, repo-relative.
    Purely informational — never part of an event's identity/allowlist
    key (tracebacks move with unrelated edits)."""
    if eqn is None:
        return ""
    try:
        from jax._src import source_info_util

        s = source_info_util.summarize(eqn.source_info)
    except Exception:
        return ""
    for anchor in ("ringpop_tpu/", "tests/"):
        i = s.find(anchor)
        if i > 0:
            return s[i:]
    return s


@dataclasses.dataclass(frozen=True)
class RangeEvent:
    """One certifier hit, pre-rendering: ``key`` is the stable identity
    the overflow prong's allowlist matches on (never includes interval
    endpoints, which move as the analysis gets sharper)."""

    rule: str  # dtype-overflow | unbounded-carry | index-overflow
    loc: str  # "/".join(walk stack), "<top>" at depth 0
    prim: str
    key: str
    detail: str
    src: str = ""  # "path/to/file.py:123 (fn)" from jaxpr source info


class RangeVisitor(dataflow.Visitor):
    """The interval interpreter as a :class:`dataflow.Visitor`.

    Values are ``Optional[Interval]`` (None = top).  ``join`` is the
    WIDENING join — :func:`dataflow.walk` calls it only on the
    scan/while carry feedback loop, which is exactly where widening
    belongs; everything inside :meth:`eqn_out` uses the precise
    :func:`union`.  Signed results that escape their dtype are reported
    once (at the first equation that manufactures the escape from
    in-range inputs) and kept UNCLAMPED so carry growth stays visible
    to the fixpoint; unsigned results wrap silently to full range (the
    repo's mod-2^32 contract).
    """

    bottom = None
    precise = True
    fixpoint = True

    def __init__(
        self,
        spec: Optional[ScaleSpec] = None,
        invar_names: Optional[Dict[object, str]] = None,
    ):
        self.spec = spec or ScaleSpec()
        self.invar_names = invar_names or {}
        # (rule, loc, prim, key) -> RangeEvent; dict so fixpoint
        # revisits of a loop body overwrite instead of duplicate
        self._events: Dict[Tuple[str, str, str, str], RangeEvent] = {}

    # -- lattice ----------------------------------------------------------
    def join(self, a, b):
        return widen(a, b)

    def measure(self, val):
        return None if val is None else (val.lo, val.hi)

    def seed_constvar(self, var, const):
        return self._concrete(const)

    def literal(self, lit):
        return self._concrete(lit.val)

    @staticmethod
    def _concrete(val) -> Optional[Interval]:
        arr = np.asarray(val)
        if not _is_int_like(arr.dtype):
            return None
        if arr.size == 0:
            return Interval(0, 0)
        return Interval(int(arr.min()), int(arr.max()))

    # -- events -----------------------------------------------------------
    def events(self) -> List[RangeEvent]:
        return list(self._events.values())

    def _emit(
        self, rule: str, loc: str, prim: str, key: str, detail: str, eqn=None
    ):
        ident = (rule, loc, prim, key)
        self._events[ident] = RangeEvent(
            rule, loc or "<top>", prim, key, detail, _eqn_src(eqn)
        )

    # -- equation transfer -------------------------------------------------
    def eqn_out(self, eqn, stack, in_vals, subs, sub_out_vals):
        prim = eqn.primitive.name
        loc = "/".join(stack)
        n_out = len(eqn.outvars)
        if subs:
            raw = self._from_subs(eqn, loc, in_vals, subs, sub_out_vals)
        else:
            raw = self._transfer(prim, eqn, loc, in_vals)
        self._index_checks(prim, eqn, loc)
        if len(raw) < n_out:
            raw = list(raw) + [None] * (n_out - len(raw))
        return [
            self._finalize(eqn, loc, prim, in_vals, var, raw[i])
            for i, var in enumerate(eqn.outvars)
        ]

    # sub-jaxpr boundary: positional prefix union; cond branches union;
    # unmapped boundaries (pallas kernels) stay top; scan/while carries
    # get the zero-iteration identity and the escaped-dtype check
    def _from_subs(self, eqn, loc, in_vals, subs, sub_out_vals):
        n_out = len(eqn.outvars)
        EMPTY = object()
        outs: List[object] = [EMPTY] * n_out

        def merge(i, v):
            outs[i] = v if outs[i] is EMPTY else union(outs[i], v)

        for sub, ov in zip(subs, sub_out_vals):
            if sub.control:
                continue  # a while condition's value never leaves the eqn
            if not sub.out_positional:
                for i in range(n_out):
                    merge(i, None)
                continue
            for i in range(min(n_out, len(ov))):
                merge(i, ov[i])
            if sub.carry_feedback and sub.in_map is not None:
                for oi, ii in sub.carry_feedback:
                    if oi < n_out and ii < len(sub.in_map):
                        merge(oi, in_vals[sub.in_map[ii]])
        result = [None if o is EMPTY else o for o in outs]

        for sub, ov in zip(subs, sub_out_vals):
            if not sub.carry_feedback:
                continue
            for oi, ii in sub.carry_feedback:
                if oi >= n_out:
                    continue
                var = eqn.outvars[oi]
                dt = getattr(getattr(var, "aval", None), "dtype", None)
                if dt is None or not _is_signed(dt):
                    continue  # unsigned carries wrap by contract
                rng = dtype_interval(dt)
                v = result[oi]
                if v is None:
                    continue  # top from an unmodeled source, not growth
                if (v.hi is None or v.hi > rng.hi) or (
                    v.lo is None or v.lo < rng.lo
                ):
                    name = self._carry_name(eqn, sub, ii, oi)
                    self._emit(
                        "unbounded-carry",
                        loc,
                        eqn.primitive.name,
                        name,
                        f"{dt} loop carry '{name}' widens to {v} across "
                        f"iterations — a per-tick-growing counter wraps "
                        f"{dt} under the {TICK_CEILING}-tick serving "
                        "envelope's extension",
                        eqn=eqn,
                    )
        return result

    def _carry_name(self, eqn, sub, ii: int, oi: int) -> str:
        if sub.in_map is not None and ii < len(sub.in_map):
            var = eqn.invars[sub.in_map[ii]]
            try:
                name = self.invar_names.get(var)
            except TypeError:  # a Literal carry slot is unhashable
                name = None
            if name:
                return name
        return f"carry[{oi}]"

    # -- finalize one output var -------------------------------------------
    def _finalize(self, eqn, loc, prim, in_vals, var, raw):
        dt = getattr(getattr(var, "aval", None), "dtype", None)
        if dt is None or not _is_int_like(dt):
            return None
        rng = dtype_interval(dt)
        if raw is None:
            return rng
        exceeds = (
            raw.lo is None
            or raw.hi is None
            or raw.lo < rng.lo
            or raw.hi > rng.hi
        )
        if not exceeds:
            return raw
        if _is_signed(dt) and self._inputs_tame(eqn, in_vals):
            self._emit(
                "dtype-overflow",
                loc,
                prim,
                f"{prim}.out{_out_index(eqn, var)}",
                f"'{prim}' result range {raw} escapes {dt} "
                f"{rng} from in-range inputs",
                eqn=eqn,
            )
        if _is_signed(dt):
            # keep the escape visible to downstream carries; inputs are
            # no longer "tame", so the escape reports exactly once
            return raw
        return rng  # unsigned: mod-2^n wrap is the contract

    @staticmethod
    def _inputs_tame(eqn, in_vals) -> bool:
        """All integer inputs sit strictly inside their own dtype
        ranges — the overflow is newly manufactured HERE, not inherited
        from an already-reported upstream escape.  A wide-int input
        saturated AT its dtype edge counts as suspect too: that's a
        widened loop carry, an unmodeled-primitive top, or a wrapped
        lane — in all three the actionable report lives upstream (the
        named ``unbounded-carry``), not at every downstream ``+1``."""
        for var, val in zip(eqn.invars, in_vals):
            dt = getattr(getattr(var, "aval", None), "dtype", None)
            if dt is None or not _is_int_like(dt):
                continue
            if val is None:
                return False
            rng = dtype_interval(dt)
            if (
                val.lo is None
                or val.hi is None
                or val.lo < rng.lo
                or val.hi > rng.hi
            ):
                return False
            npdt = _np_dtype(dt)
            if (
                npdt is not None
                and npdt.kind in ("i", "u")
                and npdt.itemsize >= 4
                and (val.lo == rng.lo or val.hi == rng.hi)
            ):
                return False
        return True

    # -- primitive transfer -------------------------------------------------
    def _transfer(self, prim, eqn, loc, in_vals):
        n_out = len(eqn.outvars)
        v = in_vals
        if prim in ("add", "add_any"):
            return [iv_add(v[0], v[1])]
        if prim == "sub":
            return [iv_sub(v[0], v[1])]
        if prim == "mul":
            return [iv_mul(v[0], v[1])]
        if prim == "neg":
            return [iv_neg(v[0])]
        if prim == "abs":
            return [iv_abs(v[0])]
        if prim == "sign":
            return [Interval(-1, 1)]
        if prim == "max":
            return [iv_max(v[0], v[1])]
        if prim == "min":
            return [iv_min(v[0], v[1])]
        if prim == "div":
            return [iv_div(v[0], v[1])]
        if prim == "rem":
            return [iv_rem(v[0], v[1])]
        if prim == "clamp":
            return [iv_min(iv_max(v[1], v[0]), v[2])]
        if prim == "select_n":
            out = v[1] if len(v) > 1 else None
            for w in v[2:]:
                out = union(out, w)
            return [out]
        if prim == "convert_element_type":
            return [self._convert(eqn, v[0])]
        if prim == "iota":
            size = eqn.outvars[0].aval.shape[eqn.params["dimension"]]
            return [Interval(0, max(size - 1, 0))]
        if prim in (
            "broadcast_in_dim",
            "reshape",
            "transpose",
            "rev",
            "squeeze",
            "expand_dims",
            "slice",
            "dynamic_slice",
            "copy",
            "copy_p",
            "device_put",
            "reduce_precision",
            "stop_gradient",
            "gather",
            "optimization_barrier",
        ):
            return [v[0] if i < len(v) else None for i in range(n_out)]
        if prim == "sort":
            return list(v[:n_out])
        if prim == "dynamic_update_slice":
            return [union(v[0], v[1])]
        if prim == "concatenate":
            out = v[0]
            for w in v[1:]:
                out = union(out, w)
            return [out]
        if prim == "pad":
            return [union(v[0], v[1])]
        if prim.startswith("scatter"):
            return [self._scatter(prim, eqn, v)]
        if prim == "reduce_sum":
            return [self._reduce_sum(eqn, loc, v)]
        if prim == "cumsum":
            size = eqn.invars[0].aval.shape[eqn.params["axis"]]
            return [iv_mul(v[0], Interval(min(1, size), max(size, 1)))]
        if prim in ("reduce_max", "reduce_min", "reduce_or", "reduce_and"):
            return [v[0]]
        if prim in ("argmax", "argmin"):
            axes = eqn.params.get("axes", ())
            size = 1
            for a in axes:
                size *= eqn.invars[0].aval.shape[a]
            return [Interval(0, max(size - 1, 0))]
        if prim in ("eq", "ne", "lt", "le", "gt", "ge", "is_finite"):
            return [BOOL]
        if prim == "and":
            return [iv_and(v[0], v[1])]
        if prim in ("or", "xor"):
            return [iv_orxor(v[0], v[1])]
        if prim == "not":
            dt = eqn.outvars[0].aval.dtype
            if _np_dtype(dt) == np.dtype(bool):
                return [BOOL]
            return [None]
        if prim == "shift_left":
            return [iv_shl(v[0], v[1])]
        if prim in ("shift_right_logical", "shift_right_arithmetic"):
            return [iv_shr(v[0], v[1])]
        if prim == "population_count":
            bits = _np_dtype(eqn.invars[0].aval.dtype).itemsize * 8
            return [Interval(0, bits)]
        if prim == "clz":
            bits = _np_dtype(eqn.invars[0].aval.dtype).itemsize * 8
            return [Interval(0, bits)]
        if prim == "integer_pow":
            return [self._integer_pow(v[0], eqn.params.get("y", 1))]
        if prim == "dot_general":
            dims = eqn.params["dimension_numbers"][0][0]
            k = 1
            for d in dims:
                k *= eqn.invars[0].aval.shape[d]
            return [iv_scale(iv_mul(v[0], v[1]), max(k, 1))]
        return [None] * n_out

    @staticmethod
    def _integer_pow(a: Optional[Interval], y: int) -> Optional[Interval]:
        if a is None or a.lo is None or a.hi is None or y < 0:
            return None
        cands = [a.lo**y, a.hi**y]
        if a.lo <= 0 <= a.hi:
            cands.append(0)
        return Interval(min(cands), max(cands))

    def _convert(self, eqn, val) -> Optional[Interval]:
        src = _np_dtype(eqn.invars[0].aval.dtype)
        dst = _np_dtype(eqn.outvars[0].aval.dtype)
        if (
            src is not None
            and dst is not None
            and src.kind in ("i", "u")
            and dst.kind in ("i", "u")
            and src.itemsize == dst.itemsize
            and src.kind != dst.kind
        ):
            # same-width signed<->unsigned reinterpret: the sanctioned
            # bit-cast idiom (uint32 hash lanes through int32 plumbing)
            rng = dtype_interval(dst)
            if val is None:
                return rng
            exceeds = (
                val.lo is None
                or val.hi is None
                or val.lo < rng.lo
                or val.hi > rng.hi
            )
            return rng if exceeds else val
        return val  # value-preserving intent; _finalize flags the escape

    def _scatter(self, prim, eqn, v) -> Optional[Interval]:
        operand, updates = v[0], v[2] if len(v) > 2 else None
        if prim == "scatter":
            return union(operand, updates)
        if prim == "scatter-add":
            upd_aval = eqn.invars[2].aval
            count = 1
            for d in upd_aval.shape:
                count *= d
            bump = iv_scale(updates, max(count, 1))
            if bump is None:
                return None
            # additive: only the signs that can actually accumulate move
            lo = operand.lo if operand is not None else None
            hi = operand.hi if operand is not None else None
            if lo is not None:
                lo = lo + min(bump.lo, 0) if bump.lo is not None else None
            if hi is not None:
                hi = hi + max(bump.hi, 0) if bump.hi is not None else None
            return Interval(lo, hi)
        return None  # scatter-mul / -max / -min: dtype top

    def _reduce_sum(self, eqn, loc, v) -> Optional[Interval]:
        shape = eqn.invars[0].aval.shape
        axes = eqn.params["axes"]
        count = 1
        scaled = 1
        for a in axes:
            count *= shape[a]
            scaled *= scaled_dim(shape[a], self.spec)
        out = iv_scale(v[0], max(count, 1))
        dt = _np_dtype(eqn.outvars[0].aval.dtype)
        if (
            scaled != count
            and dt is not None
            and dt.kind == "i"
            and v[0] is not None
            and self._inputs_tame(eqn, [v[0]])
        ):
            at_scale = iv_scale(v[0], max(scaled, 1))
            rng = dtype_interval(dt)
            if (
                at_scale is not None
                and at_scale.lo is not None
                and at_scale.hi is not None
                and (at_scale.lo < rng.lo or at_scale.hi > rng.hi)
            ):
                self._emit(
                    "dtype-overflow",
                    loc,
                    "reduce_sum",
                    f"reduce_sum.scaled.{shape}",
                    f"reduce_sum over a scaled axis ({count} -> {scaled} "
                    f"at {self.spec.label()}) accumulates {at_scale}, "
                    f"escaping {dt} {rng} — fine at the n={self.spec.toy_n} "
                    "trace, wraps at the declared ceiling",
                    eqn=eqn,
                )
        return out

    # -- shape-derived index-space safety at the declared ceiling -----------
    def _index_checks(self, prim, eqn, loc):
        spec = self.spec
        checks: List[Tuple[object, int, str]] = []  # (idx dtype, extent, tag)
        if prim == "iota":
            axis = eqn.params["dimension"]
            shape = eqn.outvars[0].aval.shape
            dt = eqn.outvars[0].aval.dtype
            checks.append((dt, scaled_dim(shape[axis], spec), f"iota.{axis}"))
        elif prim == "gather":
            dnums = eqn.params["dimension_numbers"]
            op_shape = eqn.invars[0].aval.shape
            idx_dt = eqn.invars[1].aval.dtype
            for d in dnums.start_index_map:
                checks.append(
                    (idx_dt, scaled_dim(op_shape[d], spec), f"gather.dim{d}")
                )
        elif prim.startswith("scatter"):
            dnums = eqn.params["dimension_numbers"]
            op_shape = eqn.invars[0].aval.shape
            idx_dt = eqn.invars[1].aval.dtype
            for d in dnums.scatter_dims_to_operand_dims:
                checks.append(
                    (idx_dt, scaled_dim(op_shape[d], spec), f"{prim}.dim{d}")
                )
        elif prim in ("dynamic_slice", "dynamic_update_slice"):
            op_shape = eqn.invars[0].aval.shape
            first_idx = 2 if prim == "dynamic_update_slice" else 1
            if len(eqn.invars) > first_idx:
                idx_dt = eqn.invars[first_idx].aval.dtype
                for d, size in enumerate(op_shape):
                    checks.append(
                        (idx_dt, scaled_dim(size, spec), f"{prim}.dim{d}")
                    )
        for dt, extent, tag in checks:
            rng = dtype_interval(dt)
            if rng is None or rng.hi is None:
                continue
            if extent - 1 > rng.hi:
                self._emit(
                    "index-overflow",
                    loc,
                    prim,
                    tag,
                    f"'{prim}' index lane is {_np_dtype(dt)} but the "
                    f"indexed extent reaches {extent} at the declared "
                    f"ceiling ({spec.label()}) — index space escapes the "
                    "dtype before the engine reaches its contract scale",
                    eqn=eqn,
                )


def analyze_jaxpr(
    closed,
    spec: Optional[ScaleSpec] = None,
    invar_names: Optional[Sequence[Optional[str]]] = None,
) -> List[RangeEvent]:
    """Run the interval certifier over one ClosedJaxpr.

    ``invar_names[i]`` optionally names flattened input leaf ``i``
    (state-field paths from ``noninterference.label_tree``) so carry
    findings are attributable; the list must align with
    ``closed.jaxpr.invars`` when given.
    """
    jaxpr = closed.jaxpr
    names: Dict[object, str] = {}
    if invar_names is not None and len(invar_names) == len(jaxpr.invars):
        for var, name in zip(jaxpr.invars, invar_names):
            if name:
                names[var] = name
    visitor = RangeVisitor(spec=spec, invar_names=names)
    in_vals = [input_contract(v.aval) for v in jaxpr.invars]
    dataflow.walk(jaxpr, closed.consts, (), in_vals, visitor)
    return visitor.events()


def buffer_poly(closed, spec: ScaleSpec) -> Dict[int, int]:
    """Abstract footprint of a traced entry as a polynomial in N.

    Sums the at-scale byte size of EVERY SSA value in the program —
    inputs, every equation output, recursively through all sub-jaxprs —
    as ``{exponent: coeff_bytes}`` where the exponent counts scaled
    dims (``poly[1]`` is the O(N) coefficient, ``poly[2]`` the O(N²)
    one).  This deliberately overcounts live memory (no liveness, scan
    bodies priced once but intermediates all summed): an UPPER bound
    XLA's buffer assignment only improves on, which is the right
    direction for a feasibility ceiling.
    """
    poly: Dict[int, float] = {}

    def price(var):
        aval = getattr(var, "aval", None)
        shape = getattr(aval, "shape", None)
        dt = _np_dtype(getattr(aval, "dtype", None))
        if shape is None or dt is None:
            return
        coeff = dt.itemsize
        exp = 0
        for d in shape:
            kind, v = _dim_rule(d, spec)
            if kind == "scaled":
                # dim = c*toy_n rides the N axis: bytes go up a degree
                exp += 1
                coeff *= v
            else:
                # trace constant, or a dim_map capacity envelope: a
                # constant factor at its declared at-scale extent
                coeff *= v
        poly[exp] = poly.get(exp, 0) + coeff

    def visit(jaxpr):
        import jax

        for var in jaxpr.invars:
            price(var)
        for eqn in jaxpr.eqns:
            for var in eqn.outvars:
                if isinstance(var, jax.core.DropVar):
                    continue
                price(var)
            for sub in dataflow.sub_jaxprs(eqn, precise=True):
                inner, _ = sub.open_()
                visit(inner)

    visit(closed.jaxpr)
    return {e: int(math.ceil(c)) for e, c in sorted(poly.items())}


def poly_bytes(poly: Dict[int, int], n: int) -> int:
    return sum(c * n**e for e, c in poly.items())


def feasible_n(poly: Dict[int, int], budget_bytes: int, n_max: int) -> int:
    """Largest N <= n_max with poly(N) <= budget (binary search; 0 when
    even the constant term busts the budget)."""
    if poly_bytes(poly, 1) > budget_bytes:
        return 0
    lo, hi = 1, n_max
    while lo < hi:
        mid = (lo + hi + 1) // 2
        if poly_bytes(poly, mid) <= budget_bytes:
            lo = mid
        else:
            hi = mid - 1
    return lo


def _out_index(eqn, var) -> int:
    for i, ov in enumerate(eqn.outvars):
        if ov is var:
            return i
    return 0
