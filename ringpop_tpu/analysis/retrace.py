"""jaxgate prong A': retrace-budget probes against a committed manifest.

A silent retrace on the parity hot path costs seconds per occurrence on
the chip tunnel and usually signals a shape- or structure-dependent bug
(a Python branch on a traced value, a pytree whose structure flips
between calls).  Each probe here builds a FRESH jitted entry point and
drives it through a fixed call sequence:

1. canonical shape, values A        -> must compile (cache size 1)
2. same shape, different values     -> must HIT the cache (still 1)
3. a legitimately different shape / pytree structure -> must MISS (2)

After every step the probe records ``fn._cache_size()``.  The expected
sequences live in ``ANALYSIS_BUDGET.json`` at the repo root; a mismatch —
either direction — is a finding.  Extra compiles mean a silent retrace
crept in; fewer mean the manifest is stale and must be regenerated with
``scripts/check_retrace_budget.py --write`` (an intentional, reviewed
change).
"""

from __future__ import annotations

import dataclasses
import json
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Tuple

from ringpop_tpu.analysis.findings import Finding

MANIFEST_NAME = "ANALYSIS_BUDGET.json"


@dataclasses.dataclass(frozen=True)
class Probe:
    name: str
    # () -> (jitted_fn, [(step description, args tuple), ...])
    build: Callable[[], Tuple[Callable, List[Tuple[str, Tuple]]]]


def run_probe(probe: Probe) -> List[dict]:
    import jax

    # a fresh jit-cache baseline per probe: pjit executable caches are
    # keyed on the UNDERLYING callable, not the jit wrapper, so a probe
    # wrapping a shared object (the lru-cached exchange-plane fixture,
    # a bound plane method) inherits whatever entries earlier tests in
    # the same process compiled at other shapes — its step counts then
    # start above the committed baseline ("silent retrace" noise under
    # full-suite ordering).  Clearing is cheap under the persistent XLA
    # compilation cache: recompiles become disk loads.
    jax.clear_caches()
    fn, steps = probe.build()
    out: List[dict] = []
    for desc, args in steps:
        fn(*args)
        out.append({"desc": desc, "cache_size": int(fn._cache_size())})
    return out


def run_probes(probes: Optional[Iterable[Probe]] = None) -> Dict[str, list]:
    """Run every probe; a probe whose entry point breaks yields a single
    ``{"error": ...}`` step instead of crashing the tool (the jaxpr
    prong's trace-failure analog — compare_to_manifest turns it into a
    finding, write_manifest refuses to commit it)."""
    out: Dict[str, list] = {}
    for p in DEFAULT_PROBES if probes is None else probes:
        try:
            out[p.name] = run_probe(p)
        except Exception as e:
            out[p.name] = [
                {"error": f"{type(e).__name__}: {e}"}
            ]
    return out


def compare_to_manifest(
    actual: Dict[str, list], manifest: dict
) -> List[Finding]:
    findings: List[Finding] = []
    expected = manifest.get("probes", {})
    for name, exp_steps in sorted(expected.items()):
        if name not in actual:
            findings.append(
                Finding(
                    rule="retrace-budget",
                    path=f"<probe:{name}>",
                    line=0,
                    message="probe in manifest but not run",
                    prong="retrace",
                )
            )
            continue
        act_steps = actual[name]
        if any("error" in s for s in act_steps):
            err = next(s["error"] for s in act_steps if "error" in s)
            findings.append(
                Finding(
                    rule="probe-failure",
                    path=f"<probe:{name}>",
                    line=0,
                    message=f"probe failed to run: {err}",
                    prong="retrace",
                )
            )
            continue
        if len(act_steps) != len(exp_steps):
            findings.append(
                Finding(
                    rule="retrace-budget",
                    path=f"<probe:{name}>",
                    line=0,
                    message=(
                        f"step count changed: manifest {len(exp_steps)}, "
                        f"probe ran {len(act_steps)}"
                    ),
                    prong="retrace",
                )
            )
            continue
        for i, (exp, act) in enumerate(zip(exp_steps, act_steps)):
            if act["cache_size"] != exp["cache_size"]:
                direction = (
                    "silent retrace"
                    if act["cache_size"] > exp["cache_size"]
                    else "stale manifest (fewer compiles than committed)"
                )
                findings.append(
                    Finding(
                        rule="retrace-budget",
                        path=f"<probe:{name}>",
                        line=0,
                        message=(
                            f"step {i} ({act['desc']}): cache size "
                            f"{act['cache_size']} != manifest "
                            f"{exp['cache_size']} — {direction}"
                        ),
                        prong="retrace",
                    )
                )
    for name in sorted(set(actual) - set(expected)):
        errs = [s["error"] for s in actual[name] if "error" in s]
        findings.append(
            Finding(
                rule="probe-failure" if errs else "retrace-budget",
                path=f"<probe:{name}>",
                line=0,
                message=(
                    f"probe failed to run: {errs[0]}"
                    if errs
                    else (
                        "probe has no manifest entry — regenerate with "
                        "scripts/check_retrace_budget.py --write"
                    )
                ),
                prong="retrace",
            )
        )
    return findings


def manifest_path(root: Optional[Path] = None) -> Path:
    if root is None:
        root = Path(__file__).resolve().parents[2]
    return root / MANIFEST_NAME


def load_manifest(path: Optional[Path] = None) -> dict:
    p = path or manifest_path()
    with open(p) as f:
        return json.load(f)


def write_manifest(
    actual: Dict[str, list], path: Optional[Path] = None
) -> Path:
    broken = {
        name: steps[0]["error"]
        for name, steps in actual.items()
        if any("error" in s for s in steps)
    }
    if broken:
        raise ValueError(
            f"refusing to write a manifest with failed probes: {broken}"
        )
    p = path or manifest_path()
    doc = {
        "version": 1,
        "note": (
            "jaxgate retrace budget: expected jit cache sizes after each "
            "probe step (see ringpop_tpu/analysis/retrace.py).  Regenerate "
            "with scripts/check_retrace_budget.py --write after an "
            "INTENTIONAL compile-count change."
        ),
        "probes": actual,
    }
    with open(p, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return p


def check_against_manifest(
    probes: Optional[Iterable[Probe]] = None,
    path: Optional[Path] = None,
) -> List[Finding]:
    try:
        manifest = load_manifest(path)
    except FileNotFoundError:
        return [
            Finding(
                rule="retrace-budget",
                path=MANIFEST_NAME,
                line=0,
                message=(
                    "manifest missing — generate with "
                    "scripts/check_retrace_budget.py --write"
                ),
                prong="retrace",
            )
        ]
    return compare_to_manifest(run_probes(probes), manifest)


# ---------------------------------------------------------------------------
# probes


def _probe_farmhash_scan() -> Tuple[Callable, List[Tuple[str, Tuple]]]:
    import functools

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.ops import jax_farmhash as jfh

    fn = jax.jit(functools.partial(jfh.hash32_rows, impl="scan"))

    def args(b, w, seed):
        r = np.random.default_rng(seed)
        return (
            jnp.asarray(r.integers(0, 256, size=(b, w)), dtype=jnp.uint8),
            jnp.asarray(r.integers(0, w - 4, size=(b,)), dtype=jnp.int32),
        )

    return fn, [
        ("[8,64] values A", args(8, 64, 0)),
        ("[8,64] values B (expect cache hit)", args(8, 64, 1)),
        ("[8,128] wider rows (expect recompile)", args(8, 128, 2)),
    ]


def _probe_fused_checksum_xla() -> Tuple[Callable, List[Tuple[str, Tuple]]]:
    import jax

    from ringpop_tpu.analysis import jaxpr_audit as ja
    from ringpop_tpu.ops import fused_checksum as fc

    universe = ja._toy_universe(8)

    @jax.jit
    def fn(present, status, inc):
        return fc.membership_checksums(
            universe, present, status, inc, impl="xla"
        )

    def args(b, seed):
        # shared generator with the jaxpr entry (universe dropped: it is
        # closed over by fn, not a call argument)
        return ja._fused_args(n=8, b=b, seed=seed)[1:]

    return fn, [
        ("B=2 values A", args(2, 0)),
        ("B=2 values B (expect cache hit)", args(2, 1)),
        ("B=4 (expect recompile)", args(4, 2)),
    ]


def _probe_ring_lookup() -> Tuple[Callable, List[Tuple[str, Tuple]]]:
    import jax

    from ringpop_tpu.analysis import jaxpr_audit as ja

    fn = jax.jit(ja._ring_fn())
    return fn, [
        ("N=8 values A", ja._ring_args(8, 0)),
        ("N=8 values B (expect cache hit)", ja._ring_args(8, 1)),
        ("N=12 universe (expect recompile)", ja._ring_args(12, 2)),
    ]


def _probe_engine_tick() -> Tuple[Callable, List[Tuple[str, Tuple]]]:
    import functools

    import jax
    import jax.numpy as jnp

    from ringpop_tpu.analysis import jaxpr_audit as ja

    engine, params, universe, state = ja._sim_setup(8)
    fn = jax.jit(
        functools.partial(engine.tick, params=params, universe=universe)
    )
    quiet = engine.TickInputs.quiet(8)
    churn = quiet._replace(kill=jnp.zeros(8, bool).at[3].set(True))
    # resume=None -> dense array flips the pytree STRUCTURE: a legitimate,
    # budgeted recompile (cluster.py EventSchedule keeps unused planes None
    # for exactly this reason)
    resumed = quiet._replace(resume=jnp.zeros(8, bool))
    return fn, [
        ("n=8 quiet tick", (state, quiet)),
        ("n=8 churn tick, same structure (expect cache hit)", (state, churn)),
        ("n=8 resume plane present (expect recompile)", (state, resumed)),
    ]


def _probe_engine_tick_fused() -> Tuple[Callable, List[Tuple[str, Tuple]]]:
    """The round-16 fused full-fidelity tick (fused_tick="xla"): the
    fused apply/piggyback sites must hold the same cache discipline as
    the classic shape — new values cache-hit, a pytree-structure flip
    is the one budgeted recompile."""
    import functools

    import jax
    import jax.numpy as jnp

    from ringpop_tpu.analysis import jaxpr_audit as ja

    engine, params, universe, state = ja._sim_setup(8, fused_tick="xla")
    fn = jax.jit(
        functools.partial(engine.tick, params=params, universe=universe)
    )
    quiet = engine.TickInputs.quiet(8)
    churn = quiet._replace(kill=jnp.zeros(8, bool).at[3].set(True))
    resumed = quiet._replace(resume=jnp.zeros(8, bool))
    return fn, [
        ("n=8 quiet fused tick", (state, quiet)),
        ("n=8 churn tick, same structure (expect cache hit)", (state, churn)),
        ("n=8 resume plane present (expect recompile)", (state, resumed)),
    ]


def _probe_engine_scalable_tick() -> Tuple[Callable, List[Tuple[str, Tuple]]]:
    import functools

    import jax
    import jax.numpy as jnp

    from ringpop_tpu.models.sim import engine_scalable as es

    params = es.ScalableParams(n=8, u=128)
    fn = jax.jit(functools.partial(es.tick, params=params))
    state = es.init_state(params, seed=0)
    quiet = es.ChurnInputs.quiet(8)
    churn = quiet._replace(kill=jnp.zeros(8, bool).at[2].set(True))
    parted = quiet._replace(partition=jnp.zeros(8, jnp.int32))
    return fn, [
        ("n=8 quiet tick", (state, quiet)),
        ("n=8 churn tick, same structure (expect cache hit)", (state, churn)),
        ("n=8 partition plane present (expect recompile)", (state, parted)),
    ]


def _probe_exchange_xla() -> Tuple[Callable, List[Tuple[str, Tuple]]]:
    import functools

    import jax

    from ringpop_tpu.analysis import jaxpr_audit as ja
    from ringpop_tpu.ops import exchange as exch

    fn = jax.jit(functools.partial(exch.exchange, impl="xla"))
    return fn, [
        ("[8,4] values A", ja._exchange_args(8, 4, 0)),
        ("[8,4] values B (expect cache hit)", ja._exchange_args(8, 4, 1)),
        ("[16,4] more rows (expect recompile)", ja._exchange_args(16, 4, 2)),
    ]


def _probe_exchange_plane() -> Tuple[Callable, List[Tuple[str, Tuple]]]:
    import jax

    from ringpop_tpu.analysis import jaxpr_audit as ja

    # the round-14 shard_map'd exchange plane (1-device mesh — the
    # routing program is identical at any shard count, and the probe
    # must run under both the 1-device CLI env and the 8-device test
    # conftest).  Cache discipline: new mask values under the same
    # shapes must cache-hit — the plane runs once per storm tick; a
    # wider rumor mask (same n: the plane instance is built per n) is
    # the one budgeted recompile.
    plane = ja._plane_fixture()
    fn = jax.jit(plane)
    return fn, [
        ("[8,4] values A", ja._plane_args(8, 4, 0)),
        ("[8,4] values B (expect cache hit)", ja._plane_args(8, 4, 1)),
        ("[8,8] wider mask (expect recompile)", ja._plane_args(8, 8, 2)),
    ]


def _probe_engine_scalable_tick_fused() -> (
    "Tuple[Callable, List[Tuple[str, Tuple]]]"
):
    import functools

    import jax
    import jax.numpy as jnp

    from ringpop_tpu.models.sim import engine_scalable as es

    # the round-10 hot path: sortless PRP + fused exchange (XLA twin —
    # backend-portable cache counts; the Pallas lowering shares the same
    # jit cache discipline, the op is selected at trace time)
    params = es.ScalableParams(
        n=8, u=128, perm_impl="sortless", fused_exchange="xla"
    )
    fn = jax.jit(functools.partial(es.tick, params=params))
    state = es.init_state(params, seed=0)
    quiet = es.ChurnInputs.quiet(8)
    churn = quiet._replace(kill=jnp.zeros(8, bool).at[2].set(True))
    parted = quiet._replace(partition=jnp.zeros(8, jnp.int32))
    return fn, [
        ("n=8 quiet tick", (state, quiet)),
        ("n=8 churn tick, same structure (expect cache hit)", (state, churn)),
        ("n=8 partition plane present (expect recompile)", (state, parted)),
    ]


def _probe_route_tick() -> "Tuple[Callable, List[Tuple[str, Tuple]]]":
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ringpop_tpu.analysis import jaxpr_audit as ja

    # the round-11 routing plane: bucketed incremental ring + Zipf
    # traffic + counters in one traced tick.  Static buckets/reps/cdf
    # ride as closure constants (the driver's calling convention), so
    # the cache keys on the membership-plane shapes alone.
    plane, params, buckets, reps, cdf, state, _dyn = ja._route_fixture(
        "incremental"
    )

    def call(state, in_ring, proc_alive, checksums):
        return plane.route_tick(
            state, buckets, reps, cdf, in_ring, proc_alive, checksums,
            params,
        )

    fn = jax.jit(call)

    def nargs(n, seed):
        r2 = np.random.default_rng(seed)
        return (
            jnp.asarray(r2.random(n) < 0.8),
            jnp.asarray(r2.random(n) < 0.9),
            jnp.asarray(r2.integers(0, 2**32, size=n, dtype=np.uint32)),
        )

    # a wider membership plane (same pytree structure, new [N] shapes)
    # must recompile exactly once; the bucketed ring state keeps its
    # bucket-shaped arrays, only its mask widens
    state12 = state._replace(
        ring=state.ring._replace(mask=jnp.zeros(12, bool))
    )
    return fn, [
        ("n=8 route tick", (state,) + nargs(8, 1)),
        ("n=8 new values (expect cache hit)", (state,) + nargs(8, 2)),
        ("n=12 membership plane (expect recompile)", (state12,) + nargs(12, 3)),
    ]


def _probe_fuzz_scan() -> "Tuple[Callable, List[Tuple[str, Tuple]]]":
    import functools

    import jax

    from ringpop_tpu.analysis import jaxpr_audit as ja
    from ringpop_tpu.fuzz import executor as fex

    # the round-12 batched fuzz executor (scalable engine: the cheap
    # compile).  Cache discipline: new schedules/values under the same
    # [T, B, N] shapes must cache-hit — a fuzz sweep and every shrink
    # candidate batch reuse one executable; a new batch size B is the
    # one budgeted recompile (the shrinker pads candidate batches to
    # powers of two for exactly this reason).
    ex, states2, inputs2 = ja._fuzz_fixture("scalable", b=2)
    fn = jax.jit(
        functools.partial(fex.scenario_scan_scalable, params=ex.params)
    )
    _, states2b, inputs2b = ja._fuzz_fixture("scalable", b=2, seed0=7)
    _, states4, inputs4 = ja._fuzz_fixture("scalable", b=4)
    return fn, [
        ("B=2 scenario batch", (states2, inputs2)),
        ("B=2 new values (expect cache hit)", (states2b, inputs2b)),
        ("B=4 batch (expect recompile)", (states4, inputs4)),
    ]


DEFAULT_PROBES: List[Probe] = [
    Probe("farmhash-scan", _probe_farmhash_scan),
    Probe("fused-checksum-xla", _probe_fused_checksum_xla),
    Probe("ring-device-lookup", _probe_ring_lookup),
    Probe("engine-tick", _probe_engine_tick),
    Probe("engine-tick-fused", _probe_engine_tick_fused),
    Probe("engine-scalable-tick", _probe_engine_scalable_tick),
    Probe("exchange-xla", _probe_exchange_xla),
    Probe("exchange-plane", _probe_exchange_plane),
    Probe(
        "engine-scalable-tick-fused", _probe_engine_scalable_tick_fused
    ),
    Probe("route-tick", _probe_route_tick),
    Probe("fuzz-scenario-scan", _probe_fuzz_scan),
]
