"""jaxgate prong: donation/aliasing sanitizer against DONATION_BUDGET.json.

The two worst bugs the dynamic suites ever caught — PR 8's XLA-cache
donation mis-execution on CPU, PR 7's live-device-state aliasing flake
across donating dispatches — were both donation/aliasing bugs no other
prong could see.  This prong pins the donation surface statically:

- every jitted driver that donates its carry (single-sourced through
  ``storm.donate_state_argnums`` — the PR-8 CPU backend gate lives
  THERE, not here) is compiled at toy shapes and the executable's
  ``input_output_alias`` map is extracted from the optimized HLO;
- a donated leaf that no output aliases is a **silently dropped
  donation** (rule ``donation-dropped``): the caller pays the API cost
  of donation (its buffers are dead after the call) without the
  in-place win — almost always a shape/dtype mismatch between the
  donated leaf and every output, which the finding names;
- the expected alias map is pinned in a committed
  ``DONATION_BUDGET.json`` diffed like the retrace/cost budgets.  On
  CPU, ``donate_state_argnums()`` returns ``()`` (the PR-8 backend
  gate), so the committed CPU manifest shows every entry with an EMPTY
  alias map — the gate is visible manifest data, not a special case in
  this checker.  A chip session banks a TPU manifest side by side via
  ``--budget``.

Regenerate with ``scripts/check_donation_budget.py --write`` after an
INTENTIONAL donation-surface change; ``--write`` refuses entries that
failed to compile or that drop donations.
"""

from __future__ import annotations

import dataclasses
import json
import re
from pathlib import Path
from typing import Callable, Dict, Iterable, List, Optional, Sequence, Tuple

from ringpop_tpu.analysis.findings import Finding

MANIFEST_NAME = "DONATION_BUDGET.json"

_ALIAS_ENTRY_RE = re.compile(
    r"\{\s*([0-9,\s]*)\}:\s*\(([0-9]+),\s*\{([0-9,\s]*)\}"
)


def _alias_map_text(hlo_text: str) -> str:
    """The brace-balanced body of ``input_output_alias={...}`` in an
    optimized HLO module header ('' when the executable aliases nothing)."""
    marker = "input_output_alias={"
    start = hlo_text.find(marker)
    if start < 0:
        return ""
    i = start + len(marker)
    depth = 1
    out = []
    while i < len(hlo_text) and depth:
        c = hlo_text[i]
        if c == "{":
            depth += 1
        elif c == "}":
            depth -= 1
            if not depth:
                break
        out.append(c)
        i += 1
    return "".join(out)


def parse_alias_map(hlo_text: str) -> List[Tuple[Tuple[int, ...], int]]:
    """``[(output_index, param_number), ...]`` from optimized HLO text.

    jit flattens pytrees, so ``param_number`` is the flattened input
    leaf index and the output index tuple is almost always one level
    deep; the raw tuple is preserved for the manifest either way."""
    body = _alias_map_text(hlo_text)
    out: List[Tuple[Tuple[int, ...], int]] = []
    for m in _ALIAS_ENTRY_RE.finditer(body):
        out_idx = tuple(
            int(x) for x in m.group(1).replace(",", " ").split()
        )
        out.append((out_idx, int(m.group(2))))
    return out


def audit_jit(fn, args: Tuple, donate_argnums: Sequence[int]) -> dict:
    """Compile one donating jit and report its donation outcome.

    Returns ``{donated_params, aliased_params, aliases, dropped}`` where
    ``dropped`` lists ``{param, shape, dtype}`` for every donated leaf no
    output aliases.  ``fn`` must already carry its donation config (this
    helper never adds one) — ``donate_argnums`` only says which
    positional args the config covers, so the flattened leaf indices can
    be recovered.
    """
    import jax

    compiled = fn.lower(*args).compile()
    aliases = parse_alias_map(compiled.as_text())
    aliased_params = {p for _, p in aliases}

    donated_idx: Dict[int, object] = {}  # flattened leaf index -> leaf
    offset = 0
    for i, arg in enumerate(args):
        leaves = jax.tree_util.tree_flatten(arg)[0]
        if i in donate_argnums:
            for j, leaf in enumerate(leaves):
                donated_idx[offset + j] = leaf
        offset += len(leaves)

    dropped = []
    for p in sorted(set(donated_idx) - aliased_params):
        leaf = donated_idx[p]
        dropped.append(
            {
                "param": p,
                "shape": list(getattr(leaf, "shape", ())),
                "dtype": str(getattr(leaf, "dtype", "?")),
            }
        )
    return {
        "donated_params": len(donated_idx),
        "aliased_params": len(aliased_params & set(donated_idx)),
        # JSON-stable: one "out{...} <- param N" string per alias row
        "aliases": sorted(
            "out{%s} <- param %d" % (",".join(map(str, o)), p)
            for o, p in aliases
        ),
        "dropped": dropped,
    }


# ---------------------------------------------------------------------------
# entry registry: every donating jitted driver in the repo


@dataclasses.dataclass(frozen=True)
class DonationEntry:
    name: str
    build: Callable[[], Tuple[Callable, Tuple]]  # () -> (jitted fn, args)


def _scalable_fixture(t: int = 2):
    import jax

    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.models.sim import storm

    params = es.resolve_scalable_params(
        es.ScalableParams(n=8, u=128), jax.default_backend()
    )
    state = es.init_state(params, seed=0)
    one = es.ChurnInputs.quiet(8)
    stacked = jax.tree_util.tree_map(
        lambda x: jax.numpy.broadcast_to(x, (t,) + x.shape), one
    )
    return storm, params, state, one, stacked


def _entry_scalable_tick() -> Tuple[Callable, Tuple]:
    storm, params, state, one, _ = _scalable_fixture()
    return storm._tick_fn(params), (state, one)


def _entry_scalable_scan() -> Tuple[Callable, Tuple]:
    storm, params, state, _, stacked = _scalable_fixture()
    return storm._scanned_fn(params), (state, stacked)


def _routed_fixture(t: int = 2):
    import jax

    from ringpop_tpu.models.route import plane
    from ringpop_tpu.models.sim import engine_scalable as es

    rs = plane.RoutedStorm(
        n=8,
        route=plane.RouteParams(
            n=8,
            replica_points=4,
            bucket_bits=2,
            queries_per_tick=16,
            key_space=64,
            max_changed=4,
            max_dirty=4,
        ),
        replica_points=4,
    )
    one = es.ChurnInputs.quiet(8)
    stacked = jax.tree_util.tree_map(
        lambda x: jax.numpy.broadcast_to(x, (t,) + x.shape), one
    )
    carry = (rs.cluster.state, rs.rstate)
    static = (rs.buckets, rs.reps, rs.cdf)
    return rs, carry, one, stacked, static


def _entry_routed_tick() -> Tuple[Callable, Tuple]:
    rs, carry, one, _, static = _routed_fixture()
    return rs._tick, (carry, one) + static


def _entry_routed_scan() -> Tuple[Callable, Tuple]:
    rs, carry, _, stacked, static = _routed_fixture()
    return rs._scanned, (carry, stacked) + static


def _entry_mesh_storm_tick() -> Tuple[Callable, Tuple]:
    """The sharded storm's donating SPMD tick on a 1-device mesh — the
    routing program is identical at any shard count, and the alias map
    must hold under explicit shardings too (round 14)."""
    import jax

    from ringpop_tpu.models.sim import engine_scalable as es
    from ringpop_tpu.parallel import mesh as pmesh

    params = es.resolve_scalable_params(
        es.ScalableParams(n=8, u=128), jax.default_backend()
    )
    mesh = pmesh.make_mesh(1)
    fn = pmesh._storm_tick_fn(params, mesh, (True, True), None)
    state = es.init_state(params, seed=0)
    return fn, (state, es.ChurnInputs.quiet(8))


DEFAULT_ENTRIES: List[DonationEntry] = [
    DonationEntry("scalable-tick", _entry_scalable_tick),
    DonationEntry("scalable-scan", _entry_scalable_scan),
    DonationEntry("routed-tick", _entry_routed_tick),
    DonationEntry("routed-scan", _entry_routed_scan),
    DonationEntry("mesh-storm-tick", _entry_mesh_storm_tick),
]

# tier-1 cheap subset (seconds warm under the persistent XLA cache);
# the full registry runs via scripts/check_donation_budget.py / --prong
CHEAP_ENTRIES: Tuple[str, ...] = ("scalable-tick", "routed-tick")

# module suffixes that can move the donation surface — the
# --changed-only gate (any analysis/ change re-runs everything)
SOURCES: Tuple[str, ...] = (
    "models/sim/storm.py",
    "models/sim/engine_scalable.py",
    "models/route/plane.py",
    "parallel/mesh.py",
    "analysis/",
)


def collect(entry_names: Optional[Iterable[str]] = None) -> Dict[str, dict]:
    """Compile each donating driver and extract its donation outcome;
    an entry that fails to build/compile yields ``{"error": ...}``."""
    from ringpop_tpu.models.sim.storm import donate_state_argnums

    donate = donate_state_argnums()
    by_name = {e.name: e for e in DEFAULT_ENTRIES}
    wanted = sorted(by_name if entry_names is None else set(entry_names))
    out: Dict[str, dict] = {}
    for name in wanted:
        e = by_name.get(name)
        if e is None:
            out[name] = {"error": "unknown donation entry"}
            continue
        try:
            fn, args = e.build()
            out[name] = audit_jit(fn, args, donate)
        except Exception as exc:
            out[name] = {"error": f"{type(exc).__name__}: {exc}"}
    return out


def compare_to_manifest(
    actual: Dict[str, dict], manifest: dict
) -> List[Finding]:
    findings: List[Finding] = []

    def finding(rule, name, msg):
        findings.append(
            Finding(
                rule=rule,
                path=f"<entry:{name}>",
                line=0,
                message=msg,
                prong="donation",
            )
        )

    expected = manifest.get("entries", {})
    for name, exp in sorted(expected.items()):
        act = actual.get(name)
        if act is None:
            finding(
                "donation-budget", name, "entry in manifest but not measured"
            )
            continue
        if "error" in act:
            finding(
                "donation-failure",
                name,
                f"entry failed to compile: {act['error']}",
            )
            continue
        for d in act["dropped"]:
            finding(
                "donation-dropped",
                name,
                (
                    "donated leaf param %d (%s[%s]) is not consumed by any "
                    "input_output_alias — the donation is silently dropped "
                    "(no output matches its shape/dtype); drop the leaf "
                    "from the donated carry or fix the mismatch"
                )
                % (
                    d["param"],
                    d["dtype"],
                    ",".join(map(str, d["shape"])),
                ),
            )
        for key in ("donated_params", "aliased_params", "aliases"):
            if act.get(key) != exp.get(key):
                finding(
                    "donation-budget",
                    name,
                    (
                        f"{key} changed: measured {act.get(key)!r} vs "
                        f"manifest {exp.get(key)!r} — regenerate with "
                        "scripts/check_donation_budget.py --write if "
                        "intentional"
                    ),
                )
    for name in sorted(set(actual) - set(expected)):
        act = actual[name]
        if "error" in act:
            finding(
                "donation-failure",
                name,
                f"entry failed to compile: {act['error']}",
            )
        else:
            finding(
                "donation-budget",
                name,
                (
                    "entry has no manifest entry — regenerate with "
                    "scripts/check_donation_budget.py --write"
                ),
            )
    return findings


def manifest_path(root: Optional[Path] = None) -> Path:
    if root is None:
        root = Path(__file__).resolve().parents[2]
    return root / MANIFEST_NAME


def load_manifest(path: Optional[Path] = None) -> dict:
    with open(path or manifest_path()) as f:
        return json.load(f)


def write_manifest(
    actual: Dict[str, dict], path: Optional[Path] = None
) -> Path:
    """Commit the donation outcome.  REFUSES failed entries AND dropped
    donations — a manifest must never bless a silent drop."""
    import jax

    from ringpop_tpu.models.sim.storm import donate_state_argnums

    broken = {
        n: e["error"] for n, e in actual.items() if "error" in e
    }
    if broken:
        raise ValueError(
            f"refusing to write a manifest with failed entries: {broken}"
        )
    dropping = {
        n: e["dropped"] for n, e in actual.items() if e.get("dropped")
    }
    if dropping:
        raise ValueError(
            "refusing to write a manifest with dropped donations "
            f"(fix the shape/dtype mismatch instead): {dropping}"
        )
    p = path or manifest_path()
    doc = {
        "version": 1,
        "backend": jax.default_backend(),
        "jax_version": jax.__version__,
        # the PR-8 CPU gate, recorded as DATA: () on CPU means every
        # entry below legitimately aliases nothing
        "donate_argnums": list(donate_state_argnums()),
        "note": (
            "jaxgate donation budget: expected input_output_alias "
            "surface of every donating jitted driver at toy shapes (see "
            "ringpop_tpu/analysis/donation.py).  Regenerate with "
            "scripts/check_donation_budget.py --write after an "
            "INTENTIONAL donation-surface change."
        ),
        "entries": actual,
    }
    with open(p, "w") as f:
        json.dump(doc, f, indent=2, sort_keys=True)
        f.write("\n")
    return p


def check_against_manifest(
    entry_names: Optional[Iterable[str]] = None,
    path: Optional[Path] = None,
) -> List[Finding]:
    """The gate: compile + diff.  Each backend banks its OWN manifest
    (the CPU one pins the PR-8 donation-off gate as empty alias maps) —
    and a backend mismatch is a LOUD finding, not a silent skip: the
    mismatch case is precisely a donating backend (TPU) running against
    the donation-off CPU manifest, where a dropped donation would
    otherwise sail through green."""
    import jax

    try:
        manifest = load_manifest(path)
    except FileNotFoundError:
        return [
            Finding(
                rule="donation-budget",
                path=MANIFEST_NAME,
                line=0,
                message=(
                    "manifest missing — generate with "
                    "scripts/check_donation_budget.py --write"
                ),
                prong="donation",
            )
        ]
    backend = jax.default_backend()
    if manifest.get("backend") != backend:
        return [
            Finding(
                rule="donation-budget",
                path=MANIFEST_NAME,
                line=0,
                message=(
                    "manifest was banked on backend "
                    f"{manifest.get('backend')!r} but this run is on "
                    f"{backend!r} — donation surfaces do not transfer "
                    "across backends; bank one for this backend with "
                    "scripts/check_donation_budget.py --write --budget "
                    f"DONATION_BUDGET_{backend.upper()}.json"
                ),
                prong="donation",
            )
        ]
    explicit_subset = entry_names is not None
    actual = collect(entry_names)
    if explicit_subset:
        sliced = dict(manifest)
        sliced["entries"] = {
            k: v
            for k, v in manifest.get("entries", {}).items()
            if k in actual
        }
        return compare_to_manifest(actual, sliced)
    return compare_to_manifest(actual, manifest)
