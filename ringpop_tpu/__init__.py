"""ringpop_tpu — a TPU-native framework with the capabilities of Uber's ringpop.

SWIM gossip membership + consistent hash ring + request routing, rebuilt from
scratch for TPU: membership state lives in (sharded) device arrays, protocol
periods run as `lax.scan` steps, gossip exchange is a batched gather/scatter
over an N-node axis, and the FarmHash-based membership/ring checksums are
computed by bit-exact hash kernels so results can be verified against the
Node.js reference (reference layout: /root/reference, ringpop v10.9.6).

Package layout
--------------
- ``ops``      — hash kernels (FarmHash32: C++ host oracle, numpy batch,
                 in-jit JAX, Pallas TPU), checksum-string encoding, ring table
                 kernels.
- ``models``   — the protocol "models": membership state machine, hash ring,
                 gossip engine (dissemination/suspicion/iterator/join), and
                 the batched cluster simulator.
- ``parallel`` — device-mesh sharding of the N-node axis (jax.sharding.Mesh,
                 shard_map), collectives helpers.
- ``utils``    — config store, typed errors, stats (statsd-style + meters and
                 histograms), logging nulls, misc helpers.
- ``api``      — the Ringpop facade (bootstrap/lookup/whoami/handleOrProxy/
                 proxyReq/getStats...), admin control plane, request proxy,
                 tracer subsystem, CLI and tick-cluster harness.
- ``obs``      — unified telemetry: JSONL run recorder, statsd bridge onto
                 the reference key scheme, Prometheus text exposition
                 (``/admin/metrics``), sim trace-tap adapters.

Int64 note: SWIM incarnation numbers in the reference are `Date.now()`
millisecond timestamps (member.js:80), which do not fit in int32.  The
simulator therefore requires JAX x64 mode; importing this package enables it
(before any array is created) unless RINGPOP_TPU_NO_X64 is set.
"""

import os as _os

if not _os.environ.get("RINGPOP_TPU_NO_X64"):
    import jax as _jax

    _jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from ringpop_tpu.utils.config import Config  # noqa: E402
from ringpop_tpu.utils import errors  # noqa: E402

__all__ = [
    "Config",
    "errors",
    "__version__",
]
